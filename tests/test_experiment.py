"""End-to-end experiment tests: config round-trip, CLI parsing, a tiny staged
run with checkpoint/resume, and the graft entry points."""

import json
import os
import sys

import jax
import numpy as np
import pytest

from iwae_replication_project_tpu.experiment import run_experiment
from iwae_replication_project_tpu.utils.config import (
    ExperimentConfig,
    config_from_args,
)


def tiny_config(tmp_path, **over):
    d = dict(
        dataset="binarized_mnist", data_dir=str(tmp_path / "data"),
        n_hidden_encoder=(16,), n_hidden_decoder=(16,),
        n_latent_encoder=(4,), n_latent_decoder=(784,),
        loss_function="IWAE", k=4, batch_size=32, n_stages=2,
        eval_k=4, nll_k=8, nll_chunk=4, eval_batch_size=16,
        activity_samples=8,
        log_dir=str(tmp_path / "runs"), checkpoint_dir=str(tmp_path / "ckpt"),
    )
    d.update(over)
    return ExperimentConfig(**d)


class TestConfig:
    def test_json_roundtrip(self):
        cfg = ExperimentConfig(k=7, n_hidden_encoder=(5, 6))
        cfg2 = ExperimentConfig.from_json(cfg.to_json())
        assert cfg2 == cfg

    def test_model_and_objective_construction(self):
        cfg = ExperimentConfig()
        assert cfg.model_config().n_stochastic == 2
        assert cfg.objective_spec().name == "IWAE"
        assert cfg.run_name().startswith("IWAE-2L-k_50-binarized_mnist-s0-")

    def test_run_name_distinguishes_hyperparams(self):
        """Presets differing only in alpha/beta/p/k2/seed/switch_* must not
        collide in checkpoint_dir (ADVICE r1: collision + resume=True would
        silently restore the wrong experiment's weights)."""
        base = ExperimentConfig(loss_function="L_alpha")
        names = {base.run_name(),
                 ExperimentConfig(loss_function="L_alpha", alpha=0.25).run_name(),
                 ExperimentConfig(loss_function="L_alpha", seed=1).run_name(),
                 ExperimentConfig(loss_function="L_alpha", beta=0.05).run_name(),
                 ExperimentConfig(loss_function="L_alpha", dataset="omniglot").run_name(),
                 ExperimentConfig(loss_function="L_alpha",
                                  switch_stage=5, switch_loss="VAE").run_name()}
        assert len(names) == 6
        # same science -> same name (resume must keep working)
        assert base.run_name() == ExperimentConfig(
            loss_function="L_alpha", log_dir="elsewhere").run_name()

    def test_cli_overrides(self, tmp_path):
        p = tmp_path / "c.json"
        p.write_text(ExperimentConfig(k=7).to_json())
        cfg = config_from_args(["--config", str(p), "--k", "9",
                                "--loss-function", "CIWAE",
                                "--hidden-encoder", "32,16"])
        assert cfg.k == 9
        assert cfg.loss_function == "CIWAE"
        assert cfg.n_hidden_encoder == (32, 16)

    def test_cli_defaults(self):
        cfg = config_from_args([])
        assert cfg == ExperimentConfig()

    def test_cli_multihost_flag(self):
        assert config_from_args(["--multihost"]).multihost is True
        assert config_from_args([]).multihost is False

    def test_compute_dtype_validated(self):
        """bf16 is the default since r5, so opting OUT must be explicit and
        typo-proof: 'float32' normalizes to None, anything else raises."""
        assert ExperimentConfig().compute_dtype == "bfloat16"
        assert ExperimentConfig(compute_dtype="float32").compute_dtype is None
        assert config_from_args(
            ["--compute-dtype", "float32"]).compute_dtype is None
        with pytest.raises(ValueError, match="compute_dtype"):
            ExperimentConfig(compute_dtype="bf16")
        with pytest.raises(ValueError, match="compute_dtype"):
            ExperimentConfig(compute_dtype="float16")


class TestRunExperiment:
    @pytest.mark.slow
    def test_tiny_run_and_resume(self, tmp_path):
        cfg = tiny_config(tmp_path)
        state, history = run_experiment(cfg, max_batches_per_pass=2, eval_subset=32)
        assert len(history) == 2
        res, res2 = history[-1]
        assert np.isfinite(res["NLL"])
        assert res["stage"] == 2
        # metrics + results persisted
        run_dir = os.path.join(cfg.log_dir, cfg.run_name())
        assert os.path.exists(os.path.join(run_dir, "metrics.jsonl"))
        assert os.path.exists(os.path.join(run_dir, "results.pkl"))

        # resume: extend to 3 stages; stages 1-2 must be skipped
        cfg3 = tiny_config(tmp_path, n_stages=3)
        state2, history2 = run_experiment(cfg3, max_batches_per_pass=2, eval_subset=32)
        assert len(history2) == 1
        assert history2[0][0]["stage"] == 3

    @pytest.mark.slow
    def test_mesh_run_uses_scanned_epochs(self, tmp_path):
        """run_experiment on a (dp=4, sp=2) mesh trains via the whole-epoch
        shard_map scan and produces finite staged metrics."""
        cfg = tiny_config(tmp_path, mesh_dp=4, mesh_sp=2, k=4, batch_size=32,
                          n_stages=2)
        state, history = run_experiment(cfg, max_batches_per_pass=2,
                                        eval_subset=32)
        assert len(history) == 2
        assert np.isfinite(history[-1][0]["NLL"])

    @pytest.mark.slow
    def test_pass_block_path_matches_single_dispatch(self, tmp_path, monkeypatch):
        """The stage loop's fused-pass path (PASS_BLOCK epochs per dispatch)
        must produce the same staged metrics as per-pass dispatching."""
        import iwae_replication_project_tpu.experiment as exp

        cfg = tiny_config(tmp_path, n_stages=3, resume=False,
                          save_figures=False)
        monkeypatch.setattr(exp, "PASS_BLOCK", 3)
        _, hist_block = run_experiment(cfg, eval_subset=32)

        monkeypatch.setattr(exp, "PASS_BLOCK", 10**9)  # block never triggers
        cfg2 = tiny_config(tmp_path, n_stages=3, resume=False,
                           save_figures=False,
                           log_dir=str(tmp_path / "runs2"),
                           checkpoint_dir=str(tmp_path / "ckpt2"))
        _, hist_single = run_experiment(cfg2, eval_subset=32)
        for (ra, _), (rb, _) in zip(hist_block, hist_single):
            assert abs(ra["NLL"] - rb["NLL"]) < 1e-3, (ra["NLL"], rb["NLL"])

        # and the mesh driver path: block branch == per-pass branch on the
        # same (dp=4, sp=2) mesh (apples-to-apples, same collectives)
        monkeypatch.setattr(exp, "PASS_BLOCK", 3)
        cfg3 = tiny_config(tmp_path, n_stages=3, resume=False,
                           save_figures=False, mesh_dp=4, mesh_sp=2,
                           log_dir=str(tmp_path / "runs3"),
                           checkpoint_dir=str(tmp_path / "ckpt3"))
        _, hist_mesh_block = run_experiment(cfg3, eval_subset=32)

        monkeypatch.setattr(exp, "PASS_BLOCK", 10**9)
        cfg4 = tiny_config(tmp_path, n_stages=3, resume=False,
                           save_figures=False, mesh_dp=4, mesh_sp=2,
                           log_dir=str(tmp_path / "runs4"),
                           checkpoint_dir=str(tmp_path / "ckpt4"))
        _, hist_mesh_single = run_experiment(cfg4, eval_subset=32)
        for (ra, _), (rb, _) in zip(hist_mesh_block, hist_mesh_single):
            assert abs(ra["NLL"] - rb["NLL"]) < 1e-3, (ra["NLL"], rb["NLL"])

    @pytest.mark.slow
    @pytest.mark.parametrize("mesh_kw,pass_block,kill_at,expect_msg", [
        ({}, None, 5, "stage 3, pass 5"),
        (dict(mesh_dp=4, mesh_sp=2, k=4, batch_size=32), None, 5,
         "stage 3, pass 5"),
        # PASS_BLOCK=3: saves land at block boundaries (multiples of 3), so
        # the save schedule shifts — #1 stage1-end, #2 s2-end (its single
        # block ends the stage, no mid save), #3 s3-block1 (3 passes),
        # #4 s3-block2 (6 passes); die there -> resume at pass 7. This is
        # the production dispatch shape: the driver's long stages run fused
        # multi-pass blocks, and a mid-stage offset must re-decompose into
        # blocks bit-identically.
        ({}, 3, 4, "stage 3, pass 7"),
    ], ids=["single-device", "mesh-dp4-sp2", "pass-block"])
    def test_mid_stage_kill_resume_bit_identical(self, tmp_path, monkeypatch,
                                                 preempt_after, mesh_kw,
                                                 pass_block, kill_at,
                                                 expect_msg):
        """Preemption mid-stage must lose at most checkpoint_every_passes
        passes: kill the run right after an intra-stage save, resume, and the
        final state must be BIT-identical to an uninterrupted run (the
        whole-epoch scan carries the RNG key, so the pass stream is exactly
        reproducible regardless of where it was cut; VERDICT r4 #2). The
        mesh variant additionally covers Orbax round-tripping the replicated
        state and the sharded epoch scan's key threading; the pass-block
        variant covers the fused multi-pass dispatch path."""
        import iwae_replication_project_tpu.experiment as exp

        mbp = None if pass_block else 2  # block path needs full passes
        if pass_block:
            monkeypatch.setattr(exp, "PASS_BLOCK", pass_block)
        # uninterrupted reference (3 stages: 1+3+9 passes)
        cfgA = tiny_config(tmp_path, n_stages=3, resume=False,
                           save_figures=False,
                           log_dir=str(tmp_path / "runsA"),
                           checkpoint_dir=str(tmp_path / "ckptA"), **mesh_kw)
        stateA, histA = run_experiment(cfgA, max_batches_per_pass=mbp,
                                       eval_subset=32)

        # interrupted run: save every 2 passes, die right after the 5th save
        # (per-pass path: stage1-end, s2-pass2, s2-end, s3-pass2, s3-pass4
        # -> stage 3 with 4 of 9 passes done — mid-stage; block path: see
        # the parametrize comment)
        cfgB = tiny_config(tmp_path, n_stages=3, save_figures=False,
                           checkpoint_every_passes=2,
                           log_dir=str(tmp_path / "runsB"),
                           checkpoint_dir=str(tmp_path / "ckptB"), **mesh_kw)
        with pytest.raises(KeyboardInterrupt), preempt_after(kill_at):
            run_experiment(cfgB, max_batches_per_pass=mbp, eval_subset=32)

        # resume: must continue at the exact pass after the kill-point save —
        # NOT fall back to the end-of-stage-2 checkpoint (which would
        # reproduce the final state too, but lose the mid-stage work this
        # feature exists to keep)
        import io
        from contextlib import redirect_stdout
        buf = io.StringIO()
        with redirect_stdout(buf):
            stateB, histB = run_experiment(cfgB, max_batches_per_pass=mbp,
                                           eval_subset=32)
        assert expect_msg in buf.getvalue()
        assert len(histB) == 1 and histB[0][0]["stage"] == 3

        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), stateA.params, stateB.params)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)),
            stateA.opt_state.inner_state[0].mu,
            stateB.opt_state.inner_state[0].mu)
        np.testing.assert_array_equal(np.asarray(stateA.key),
                                      np.asarray(stateB.key))
        assert histA[-1][0]["NLL"] == histB[0][0]["NLL"]

    def test_passes_scale_shrinks_schedule(self, tmp_path):
        """passes_scale proportionally shrinks the Burda schedule (min 1 pass
        per stage), and is a science field (distinct run identity)."""
        cfg = tiny_config(tmp_path, n_stages=3, passes_scale=0.5,
                          save_figures=False)
        assert cfg.run_name() != tiny_config(tmp_path, n_stages=3).run_name()
        state, history = run_experiment(cfg, max_batches_per_pass=2,
                                        eval_subset=16)
        # stages run 1, round(3*0.5)=2, round(9*0.5)=4 passes of 1 batch each
        # (synthetic train set 1024 >= 2 batches of 32 -> 2 steps per pass)
        assert int(state.step) == (1 + 2 + 4) * 2

    def test_jsonl_schema(self, tmp_path):
        cfg = tiny_config(tmp_path, n_stages=1)
        run_experiment(cfg, max_batches_per_pass=1, eval_subset=32)
        path = os.path.join(cfg.log_dir, cfg.run_name(), "metrics.jsonl")
        rec = json.loads(open(path).read().strip().splitlines()[-1])
        for key in ("VAE", "IWAE", "NLL", "reconstruction_loss", "step",
                    "synthetic_data", "raw_means_bias", "nll_chunk",
                    "eval_batch"):
            assert key in rec, key
        # eval-RNG version stamps (effective values)
        assert rec["nll_chunk"] == cfg.nll_chunk
        assert rec["eval_batch"] == cfg.eval_batch_size
        assert bool(rec["synthetic_data"])  # tiny runs use blob fallback

    def test_stage_figures_written(self, tmp_path):
        cfg = tiny_config(tmp_path, n_stages=1)
        run_experiment(cfg, max_batches_per_pass=1, eval_subset=32)
        fig_dir = os.path.join(cfg.log_dir, cfg.run_name(), "figures")
        assert os.path.exists(os.path.join(fig_dir, "stage_01_samples.png"))
        assert os.path.exists(os.path.join(fig_dir, "stage_01_recons.png"))
        # PNGs decode to the expected grid geometry
        from PIL import Image
        img = Image.open(os.path.join(fig_dir, "stage_01_samples.png"))
        assert img.size[0] > 28 and img.size[1] > 28

    def test_driver_writes_latent_figure_for_digits(self, tmp_path):
        """On the labeled digits dataset, the staged driver adds the
        latent-space scatter to each stage's figure set."""
        cfg = tiny_config(tmp_path, dataset="digits", n_stages=1,
                          activity_samples=8)
        run_experiment(cfg, max_batches_per_pass=1, eval_subset=32)
        fig_dir = os.path.join(cfg.log_dir, cfg.run_name(), "figures")
        assert os.path.exists(os.path.join(fig_dir, "stage_01_latent.png"))

    def test_latent_scatter_written(self, tmp_path):
        """The latent-space figure (reference report pp.16-17): posterior-mean
        PCA scatter, labels aligned with the digits split."""
        import jax
        from iwae_replication_project_tpu.data import digits_labels, load_dataset
        from iwae_replication_project_tpu.models.iwae import (
            ModelConfig, init_params)
        from iwae_replication_project_tpu.utils.viz import latent_scatter

        ds = load_dataset("digits")
        y_train, y_test = digits_labels()
        assert len(y_train) == len(ds.x_train)
        assert len(y_test) == len(ds.x_test)
        cfg = ModelConfig(n_hidden_enc=(16,), n_hidden_dec=(16,),
                          n_latent_enc=(8,), n_latent_dec=(784,))
        params = init_params(jax.random.key(0), cfg)
        path = str(tmp_path / "latent.png")
        proj = latent_scatter(params, cfg, jax.random.key(1), ds.x_test[:64],
                              path, labels=y_test[:64], n_samples=16)
        assert proj.shape == (64, 2)
        assert os.path.getsize(path) > 0


def _write_amat_fixture(data_dir, n_train=64, n_test=32, with_raw=True, seed=11):
    """Fixture dataset in the reference's own formats: Larochelle `.amat`
    fixed-binarization train/test files, plus (optionally) raw MNIST idx-ubyte
    .gz files alongside — the exact on-disk layout a real replication run
    would use (`/root/reference/experiment_example.py:25-31` downloads the
    same formats)."""
    from fixture_io import write_idx_gz

    os.makedirs(data_dir, exist_ok=True)
    rs = np.random.RandomState(seed)
    gray = rs.rand(n_train + n_test, 784).astype(np.float32)
    binary = (rs.rand(*gray.shape) < gray).astype(np.float32)
    np.savetxt(os.path.join(data_dir, "binarized_mnist_train.amat"),
               binary[:n_train], fmt="%d")
    np.savetxt(os.path.join(data_dir, "binarized_mnist_test.amat"),
               binary[n_train:], fmt="%d")
    raw_means = None
    if with_raw:
        raw8 = (gray * 255).astype(np.uint8)
        write_idx_gz(os.path.join(data_dir, "train-images-idx3-ubyte.gz"),
                     raw8[:n_train])
        write_idx_gz(os.path.join(data_dir, "t10k-images-idx3-ubyte.gz"),
                     raw8[n_train:])
        raw_means = (raw8[:n_train].astype(np.float32) / 255.0).mean(axis=0)
    return binary[:n_train], binary[n_train:], raw_means


class TestReferenceFormatsEndToEnd:
    """The production composition the fixtures-only data tests never covered:
    reference-format files -> loader -> bias policy -> staged driver ->
    metrics/checkpoints/figures (VERDICT r3 Missing #2)."""

    def test_binarized_mnist_amat_staged_run(self, tmp_path, capsys):
        data_dir = str(tmp_path / "data")
        _, _, raw_means = _write_amat_fixture(data_dir, with_raw=True)
        cfg = tiny_config(tmp_path, allow_synthetic=False, n_stages=2)

        # the bias the driver's model was initialized with is the RAW idx
        # means, not the binarized-train means (flexible_IWAE.py:150-155)
        from iwae_replication_project_tpu.data import load_dataset
        ds = load_dataset("binarized_mnist", data_dir=data_dir,
                          allow_synthetic=False)
        np.testing.assert_allclose(ds.bias_means, raw_means, rtol=1e-6)

        state, history = run_experiment(cfg, eval_subset=32)
        assert len(history) == 2
        assert np.isfinite(history[-1][0]["NLL"])

        run_dir = os.path.join(cfg.log_dir, cfg.run_name())
        rec = json.loads(open(os.path.join(run_dir, "metrics.jsonl"))
                         .read().strip().splitlines()[-1])
        assert rec["synthetic_data"] == 0.0      # real files flowed through
        assert rec["raw_means_bias"] == 1.0      # reference bias policy held
        assert os.path.exists(os.path.join(run_dir, "results.pkl"))
        assert os.path.exists(os.path.join(
            run_dir, "figures", "stage_02_samples.png"))
        ckpt_root = os.path.join(cfg.checkpoint_dir, cfg.run_name())
        assert os.path.isdir(ckpt_root) and os.listdir(ckpt_root)
        # with raw MNIST present, the fallback warning must NOT fire
        out = capsys.readouterr()
        assert "WITHOUT raw MNIST" not in out.out + out.err

    def test_binarized_mnist_without_raw_warns_loudly(self, tmp_path, capsys):
        """Missing raw idx files = silent tenths-of-nats NLL divergence in the
        reference protocol; the driver must say so at runtime (VERDICT r3
        Weak #2)."""
        data_dir = str(tmp_path / "data")
        _write_amat_fixture(data_dir, with_raw=False)
        cfg = tiny_config(tmp_path, allow_synthetic=False, n_stages=1,
                          save_figures=False)
        run_experiment(cfg, max_batches_per_pass=1, eval_subset=16)
        out = capsys.readouterr()
        assert "WITHOUT raw MNIST" in out.out
        assert "WITHOUT raw MNIST" in out.err
        rec = json.loads(open(os.path.join(
            cfg.log_dir, cfg.run_name(), "metrics.jsonl"))
            .read().strip().splitlines()[-1])
        assert rec["raw_means_bias"] == 0.0
        assert rec["synthetic_data"] == 0.0

    def test_omniglot_chardata_staged_run(self, tmp_path):
        """Burda-split Omniglot chardata.mat through the full staged driver,
        exercising the per-epoch stochastic-binarization production path
        (flexible_IWAE.py:164-175)."""
        import scipy.io as sio
        data_dir = tmp_path / "data"
        data_dir.mkdir()
        rs = np.random.RandomState(12)
        sio.savemat(data_dir / "chardata.mat",
                    {"data": rs.rand(784, 64).astype(np.float32),
                     "testdata": rs.rand(784, 32).astype(np.float32)})
        cfg = tiny_config(tmp_path, dataset="omniglot", allow_synthetic=False,
                          n_stages=2, save_figures=False)
        state, history = run_experiment(cfg, eval_subset=32)
        assert len(history) == 2
        assert np.isfinite(history[-1][0]["NLL"])
        rec = json.loads(open(os.path.join(
            cfg.log_dir, cfg.run_name(), "metrics.jsonl"))
            .read().strip().splitlines()[-1])
        assert rec["synthetic_data"] == 0.0


class TestBackendDispatch:
    def test_torch_backend_runs_staged_loop(self, tmp_path):
        cfg = tiny_config(tmp_path, backend="torch", n_stages=2, nll_k=8,
                          nll_chunk=4)
        mdl, history = run_experiment(cfg, max_batches_per_pass=2, eval_subset=32)
        assert len(history) == 2
        assert np.isfinite(history[-1][0]["NLL"])
        assert os.path.exists(os.path.join(cfg.log_dir,
                                           cfg.run_name() + "-torch",
                                           "metrics.jsonl"))

    def test_multihost_rejected_on_eager_backends(self, tmp_path):
        cfg = tiny_config(tmp_path, backend="torch", multihost=True)
        with pytest.raises(ValueError, match="backend='jax'"):
            run_experiment(cfg)

    def test_unknown_backend_raises(self, tmp_path):
        cfg = tiny_config(tmp_path, backend="mxnet")
        with pytest.raises(ValueError):
            run_experiment(cfg, max_batches_per_pass=1, eval_subset=32)


class TestExampleScript:
    @pytest.mark.slow
    def test_reference_style_script_runs(self, tmp_path):
        """examples/experiment_example.py — the reference's experiment flow on
        the backend switch (BASELINE north star) — runs end-to-end in smoke
        mode on the real digits dataset and writes its artifacts."""
        import subprocess
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, "examples/experiment_example.py", "--smoke",
             "--dataset", "digits", "--out-dir", str(tmp_path)],
            capture_output=True, text=True, cwd="/root/repo", env=env,
            timeout=500)
        assert r.returncode == 0, r.stderr[-2000:]
        assert "done: 2 stages" in r.stdout
        run_dirs = os.listdir(tmp_path)
        assert len(run_dirs) == 1
        files = os.listdir(tmp_path / run_dirs[0])
        assert "results.pkl" in files
        assert any(f.startswith("IWAE-2L-k_8-epoch_") for f in files)


class TestGraftEntry:
    @pytest.mark.slow
    def test_entry_compiles(self):
        import jax
        sys.path.insert(0, "/root/repo")
        from __graft_entry__ import entry
        fn, args = entry()
        val = jax.jit(fn)(*args)
        assert np.isfinite(float(val))

    @pytest.mark.slow
    def test_dryrun_multichip_8(self, devices):
        sys.path.insert(0, "/root/repo")
        from __graft_entry__ import dryrun_multichip
        dryrun_multichip(8)
