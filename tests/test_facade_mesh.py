"""Facade fit() on a mesh must take the same scanned-epoch path as the
experiment driver (VERDICT r2 weak #3: the two production surfaces disagreed —
the facade looped per-batch host dispatches while experiment.py scanned)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from iwae_replication_project_tpu.api import FlexibleModel
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.parallel import make_mesh
from iwae_replication_project_tpu.parallel.dp import (
    make_parallel_epoch_fn,
    replicate,
)
from iwae_replication_project_tpu.training import create_train_state, make_adam


def make_x(n, seed=0):
    return (np.random.RandomState(seed).rand(n, 784) > 0.5).astype(np.float32)


@pytest.mark.slow
def test_fit_on_mesh_matches_driver_epoch_path(devices):
    """One facade fit() epoch on a (dp=4, sp=2) mesh produces bitwise the same
    params as driving make_parallel_epoch_fn directly from the same initial
    state — i.e. fit IS the scanned path (one dispatch per epoch), not a
    per-batch loop with different shuffle/RNG semantics."""
    mesh = make_mesh(dp=4, sp=2)
    x = make_x(64)
    k, batch = 8, 16

    mdl = FlexibleModel([16], [16], [8], [784], dataset_bias=None,
                        loss_function="IWAE", k=k, backend="jax",
                        mesh=mesh, seed=0).compile()
    mdl.fit(x, epochs=1, batch_size=batch)

    cfg = mdl.cfg
    opt = make_adam(1e-3)
    state = replicate(mesh, create_train_state(jax.random.PRNGKey(0), cfg,
                                               optimizer=opt))
    epoch_fn = make_parallel_epoch_fn(ObjectiveSpec("IWAE", k=k), cfg, mesh,
                                      n_train=len(x), batch_size=batch,
                                      optimizer=opt, donate=False)
    state, _ = epoch_fn(state, replicate(mesh, jnp.asarray(x)))

    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        mdl.params, state.params)


def test_fit_on_mesh_is_one_dispatch_per_epoch(devices, monkeypatch):
    """fit() under a mesh must not fall back to per-batch steps: the per-batch
    _step_fn is never invoked, and the scanned epoch fn runs once per epoch."""
    mesh = make_mesh(dp=4, sp=2)
    x = make_x(64, seed=1)
    mdl = FlexibleModel([16], [16], [8], [784], dataset_bias=None,
                        loss_function="IWAE", k=8, backend="jax",
                        mesh=mesh, seed=0).compile()

    def boom(*a, **kw):
        raise AssertionError("per-batch _step_fn used inside mesh fit()")

    monkeypatch.setattr(mdl, "_step_fn", boom)
    calls = {"n": 0}
    real_get = mdl._get_epoch_fn

    def counting_get(*a, **kw):
        fn = real_get(*a, **kw)

        def wrapped(state, xdev):
            calls["n"] += 1
            return fn(state, xdev)

        return wrapped

    monkeypatch.setattr(mdl, "_get_epoch_fn", counting_get)
    history = mdl.fit(x, epochs=3, batch_size=16)
    assert calls["n"] == 3
    assert len(history["loss"]) == 3
    assert np.all(np.isfinite(history["loss"]))
