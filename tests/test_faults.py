"""Fault-injection layer + resilience tests (ISSUE 10).

Four surfaces:

* the deterministic :class:`FaultSchedule` core (count triggers, matching,
  seeded streams, audit log) and the off-mode contract — byte-identical
  lowered programs and near-zero hook cost;
* :class:`RetryPolicy` + the self-healing ``TierClient`` (typed-error
  retries honoring ``retry_after_s``, reconnect across dropped/garbled
  connections, tail-latency hedging) — driven with fake engines at fake
  speed, faults injected through the REAL tier hook points;
* ``RemoteEngine`` reconnect semantics against a mid-request server
  restart: without a policy the poison is permanent (the pre-retry pin);
  with one, the proxy re-dials a fresh tier on the same port;
* checkpoint integrity: manifests written per save, verification catching
  truncation, restore falling back to the newest intact step, pre-manifest
  checkpoints still restoring.

The full-stack composition (real engines, SIGTERM + resume bitwise
parity, truncated-checkpoint fallback parity) is the chaos smoke
(scripts/chaos_smoke.py), a standing scripts/check.py stage.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

from test_frontend import FakeEngine, wait_until

from iwae_replication_project_tpu.serving import faults as sfaults
from iwae_replication_project_tpu.serving.frontend import (
    QuotaPolicy,
    RemoteEngine,
    ReplicaUnavailable,
    RetryPolicy,
    ServingTier,
    TierClient,
)
from iwae_replication_project_tpu.serving.frontend.client import TierError
from iwae_replication_project_tpu.utils import faults
from iwae_replication_project_tpu.utils.faults import (
    FaultRule,
    FaultSchedule,
    PreemptionGuard,
)


@pytest.fixture(autouse=True)
def _no_leaked_schedule():
    """Every test leaves the process with fault injection OFF."""
    yield
    faults.clear()


# ---------------------------------------------------------------------------
# FaultSchedule core
# ---------------------------------------------------------------------------

class TestFaultSchedule:
    def test_count_trigger_after_times_match(self):
        hits = []
        rule = FaultRule(site="s", after=2, times=2, name="r",
                         match=lambda ctx: ctx.get("tag") == "yes",
                         action=lambda fc: hits.append(fc.count))
        sched = FaultSchedule([rule], seed=7)
        for i in range(10):
            sched.fire("s", tag="yes")
            sched.fire("s", tag="no")      # unmatched: not even counted
            sched.fire("other", tag="yes")  # wrong site
        # matched visits 3 and 4 fire; visits 1-2 skipped (after), 5+ spent
        assert hits == [3, 4]
        assert sched.fired("r") == 2 and sched.fired() == 2
        assert sched.log == [("r", "s", 3), ("r", "s", 4)]

    def test_action_raise_propagates_from_fault_point(self):
        sched = faults.install(FaultSchedule(
            [FaultRule(site="s", action=faults.raise_fault("boom"))]))
        with pytest.raises(faults.FaultInjected, match="boom"):
            faults.fault_point("s")
        # times=1 spent: the next visit is clean
        faults.fault_point("s")
        assert sched.fired() == 1

    def test_raising_action_does_not_consume_later_rules(self):
        """A crash injected by an earlier rule aborts the visit (like real
        code after a raise): later due rules are neither logged as fired
        nor have their times budget spent — the audit log never claims a
        fault that was not actually injected."""
        hits = []
        sched = FaultSchedule([
            FaultRule(site="s", action=faults.raise_fault(), name="a"),
            FaultRule(site="s", action=lambda fc: hits.append(fc.count),
                      name="b"),
        ])
        with pytest.raises(faults.FaultInjected):
            sched.fire("s")
        assert sched.fired("a") == 1 and sched.fired("b") == 0
        assert hits == []
        sched.fire("s")        # rule a spent; rule b's budget is intact
        assert hits == [2] and sched.fired("b") == 1

    def test_seeded_streams_are_deterministic(self):
        def draws(seed):
            out = []
            rule = FaultRule(site="s", times=None,
                             action=lambda fc: out.append(fc.rng.random()))
            s = FaultSchedule([rule], seed=seed)
            for _ in range(5):
                s.fire("s")
            return out

        assert draws(3) == draws(3)
        assert draws(3) != draws(4)

    def test_off_mode_is_cheap(self):
        """The zero-overhead-when-off pin: 200k no-schedule hook visits in
        well under a generous bound (one global load + None check each)."""
        assert faults.active() is None
        t0 = time.perf_counter()
        for _ in range(200_000):
            faults.fault_point("serve.engine.launch")
        assert time.perf_counter() - t0 < 2.0

    def test_off_mode_programs_byte_identical(self):
        """Hooks live on the host side of every dispatch: the LOWERED
        serving program is byte-identical whether or not a schedule is
        installed — fault injection can never perturb compiled code."""
        import jax

        from iwae_replication_project_tpu.models import iwae as model
        from iwae_replication_project_tpu.serving.programs import PROGRAMS

        cfg = model.ModelConfig(x_dim=8, n_hidden_enc=(4,),
                                n_latent_enc=(2,), n_hidden_dec=(4,),
                                n_latent_dec=(8,),
                                fused_likelihood=False)
        params = model.init_params(jax.random.PRNGKey(0), cfg)
        program, _ = PROGRAMS["score"]

        def lowered():
            return program.lower(
                params, base_key=jax.random.PRNGKey(0),
                seeds=np.zeros((4,), np.int32),
                x=np.zeros((4, 8), np.float32), cfg=cfg, k=3).as_text()

        before = lowered()
        with faults.installed(FaultSchedule([
                FaultRule(site=sfaults.SITE_ENGINE_LAUNCH, times=None,
                          action=faults.raise_fault()),
                FaultRule(site=sfaults.SITE_AOT_CALL_ASYNC, times=None,
                          action=faults.raise_fault())])):
            during = lowered()
        assert before == during


# ---------------------------------------------------------------------------
# RetryPolicy / Backoff
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_backoff_deterministic_and_bounded(self):
        p = RetryPolicy(base_delay_s=0.01, max_delay_s=0.5, seed=11)
        a = [p.backoff(3).next_delay() for _ in range(1)]
        seq1 = [d for b in [p.backoff(3)] for d in (b.next_delay(),
                                                    b.next_delay(),
                                                    b.next_delay())]
        b2 = p.backoff(3)
        seq2 = [b2.next_delay(), b2.next_delay(), b2.next_delay()]
        assert seq1 == seq2                      # same seed+stream replays
        assert a[0] == seq1[0]
        other = p.backoff(4)
        assert [other.next_delay() for _ in range(3)] != seq1
        big = p.backoff(0)
        assert all(0.01 <= big.next_delay() <= 0.5 for _ in range(50))

    def test_hint_is_a_floor(self):
        p = RetryPolicy(base_delay_s=0.01, max_delay_s=0.02, seed=0)
        assert p.backoff(0).next_delay(retry_after_s=7.5) == 7.5

    def test_validation(self):
        with pytest.raises(ValueError, match="max_attempts"):
            RetryPolicy(max_attempts=0)
        with pytest.raises(ValueError, match="base_delay"):
            RetryPolicy(base_delay_s=0.5, max_delay_s=0.1)
        with pytest.raises(ValueError, match="unknown retry code"):
            RetryPolicy(retry_codes=frozenset({"not_a_code"}))
        assert not RetryPolicy().retryable("bad_request")
        assert RetryPolicy().retryable("overloaded")


# ---------------------------------------------------------------------------
# retry_after_s on the wire
# ---------------------------------------------------------------------------

class TestRetryAfterHint:
    def test_quota_exceeded_carries_exact_refill_wait(self):
        eng = FakeEngine("auto")
        tier = ServingTier([eng], quota=QuotaPolicy(rate=10.0, burst=2))
        tier.start()
        try:
            with TierClient("127.0.0.1", tier.port, client_id="t") as c:
                c.score([[0, 0, 0, 0], [0, 0, 0, 0]])       # drain the burst
                rid = c.submit("score", [[0, 0, 0, 0]])
                resp = c.drain([rid])[rid]
                assert resp["error"] == "quota_exceeded"
                # one token at 10/s refill: ~0.1s, and never negative
                assert 0.0 <= resp["retry_after_s"] <= 0.11
        finally:
            tier.stop(timeout_s=10)

    def test_overloaded_carries_tier_shed_hint(self):
        tier = ServingTier([FakeEngine("shed")], shed_retry_after_s=0.25)
        tier.start()
        try:
            with TierClient("127.0.0.1", tier.port) as c:
                rid = c.submit("score", [[0, 0, 0, 0]])
                resp = c.drain([rid])[rid]
                assert resp["error"] == "overloaded"
                assert resp["retry_after_s"] == 0.25
        finally:
            tier.stop(timeout_s=10)

    def test_bad_request_carries_no_hint(self):
        tier = ServingTier([FakeEngine("auto")])
        tier.start()
        try:
            with TierClient("127.0.0.1", tier.port) as c:
                rid = c.submit("nope", [[0, 0, 0, 0]])
                resp = c.drain([rid])[rid]
                assert resp["error"] == "bad_request"
                assert "retry_after_s" not in resp
        finally:
            tier.stop(timeout_s=10)


# ---------------------------------------------------------------------------
# the self-healing TierClient
# ---------------------------------------------------------------------------

def _policy(**over):
    kw = dict(max_attempts=6, base_delay_s=0.01, max_delay_s=0.05,
              deadline_s=10.0, seed=5)
    kw.update(over)
    return RetryPolicy(**kw)


class TestRetryingClient:
    def test_retries_until_capacity_returns(self):
        """Typed overloaded -> backoff -> resend; the quota/overload story
        finally has a caller that does what the message says."""
        eng = FakeEngine("shed")
        tier = ServingTier([eng])
        tier.start()

        def recover():
            time.sleep(0.05)
            eng.mode = "auto"

        threading.Thread(target=recover, daemon=True).start()
        try:
            with TierClient("127.0.0.1", tier.port, retry=_policy()) as c:
                assert c.score([[2, 0, 0, 0]], seed=9) == [9002.0]
                assert c.retry_stats["retries"] >= 1
        finally:
            tier.stop(timeout_s=10)

    def test_quota_retry_honors_hint_and_recovers(self, monkeypatch):
        from iwae_replication_project_tpu.serving.frontend import client as m

        slept = []
        real_sleep = time.sleep
        monkeypatch.setattr(m.time, "sleep",
                            lambda s: (slept.append(s), real_sleep(s)))
        eng = FakeEngine("auto")
        tier = ServingTier([eng], quota=QuotaPolicy(rate=50.0, burst=1))
        tier.start()
        try:
            with TierClient("127.0.0.1", tier.port, client_id="t",
                            retry=_policy()) as c:
                assert c.score([[1, 0, 0, 0]], seed=0) == [1.0]  # burst
                # bucket dry: the retry sleeps >= the exact refill hint
                assert c.score([[1, 0, 0, 0]], seed=1) == [1001.0]
                assert slept and max(slept) >= 0.015
        finally:
            tier.stop(timeout_s=10)

    def test_bad_request_is_not_retried(self):
        tier = ServingTier([FakeEngine("auto")])
        tier.start()
        try:
            with TierClient("127.0.0.1", tier.port, retry=_policy()) as c:
                with pytest.raises(TierError) as ei:
                    c.request("nope", [[0, 0, 0, 0]])
                assert ei.value.code == "bad_request"
                assert c.retry_stats["retries"] == 0
        finally:
            tier.stop(timeout_s=10)

    def test_cost_above_burst_quota_rejection_is_terminal(self):
        """quota_exceeded WITHOUT a refill hint = the cost-above-burst
        case no wait can admit: raised immediately, zero retries."""
        tier = ServingTier([FakeEngine("auto")],
                           quota=QuotaPolicy(rate=100.0, burst=2))
        tier.start()
        try:
            with TierClient("127.0.0.1", tier.port, client_id="t",
                            retry=_policy()) as c:
                with pytest.raises(TierError) as ei:
                    c.score([[0, 0, 0, 0]] * 3)       # 3 rows > burst 2
                assert ei.value.code == "quota_exceeded"
                assert ei.value.retry_after_s is None
                assert c.retry_stats["retries"] == 0
        finally:
            tier.stop(timeout_s=10)

    def test_close_is_final_no_silent_redial(self):
        tier = ServingTier([FakeEngine("auto")])
        tier.start()
        try:
            c = TierClient("127.0.0.1", tier.port, retry=_policy())
            assert c.score([[1, 0, 0, 0]], seed=0) == [1.0]
            c.close()
            with pytest.raises(ConnectionError, match="closed"):
                c.score([[1, 0, 0, 0]])
            with pytest.raises(ConnectionError, match="closed"):
                c.info()
            assert c.retry_stats["reconnects"] == 0
        finally:
            tier.stop(timeout_s=10)

    def test_dropped_connection_reconnects_same_result(self):
        """A response dropped on the wire (REAL tier hook point): the
        client reconnects, resends with the SAME seed, and gets the
        bitwise-identical answer — retries are invisible."""
        tier = ServingTier([FakeEngine("auto")])
        tier.start()
        faults.install(FaultSchedule(
            [sfaults.drop_tier_connection(after=0, times=1)], seed=1))
        try:
            with TierClient("127.0.0.1", tier.port, retry=_policy()) as c:
                assert c.score([[3, 0, 0, 0]], seed=4) == [4003.0]
                assert c.retry_stats["reconnects"] == 1
        finally:
            faults.clear()
            tier.stop(timeout_s=10)

    def test_garbled_connection_reconnects_same_result(self):
        tier = ServingTier([FakeEngine("auto")])
        tier.start()
        faults.install(FaultSchedule(
            [sfaults.garble_tier_connection(after=0, times=1)], seed=1))
        try:
            with TierClient("127.0.0.1", tier.port, retry=_policy()) as c:
                assert c.score([[5, 0, 0, 0]], seed=6) == [6005.0]
                assert c.retry_stats["reconnects"] >= 1
        finally:
            faults.clear()
            tier.stop(timeout_s=10)

    def test_no_retry_client_sees_connection_error(self):
        """The pre-retry pin: without a policy, a dropped response is a
        raised ConnectionError — the caller owns recovery."""
        tier = ServingTier([FakeEngine("auto")])
        tier.start()
        faults.install(FaultSchedule(
            [sfaults.drop_tier_connection(after=0, times=1)], seed=1))
        try:
            with TierClient("127.0.0.1", tier.port) as c:
                with pytest.raises((ConnectionError, OSError)):
                    c.score([[0, 0, 0, 0]])
        finally:
            faults.clear()
            tier.stop(timeout_s=10)

    def test_hedge_beats_slow_replica_first_wins(self):
        """Tail-latency hedging: the primary's replica never answers; the
        hedge lands on the idle peer and wins with the identical seed."""
        class SlowFirst(FakeEngine):
            def __init__(self):
                super().__init__("manual")
                self.first = True

            def submit(self, op, row, k=None, *, seed=None):
                f = super().submit(op, row, k=k, seed=seed)
                if not self.first:
                    self.finish()           # later requests answer instantly
                self.first = False
                return f

        slow, fast = SlowFirst(), FakeEngine("auto")
        tier = ServingTier([slow, fast], affinity_slack=0,
                           monitor_interval_s=0.05)
        tier.start()
        try:
            with TierClient("127.0.0.1", tier.port,
                            retry=_policy(hedge_after_s=0.1)) as c:
                t0 = time.monotonic()
                assert c.score([[4, 0, 0, 0]], seed=8) == [8004.0]
                assert time.monotonic() - t0 < 5.0
                assert c.retry_stats["hedges"] == 1
                assert c.retry_stats["hedge_wins"] == 1
        finally:
            slow.finish()
            tier.stop(timeout_s=10)


# ---------------------------------------------------------------------------
# RemoteEngine reconnect semantics (mid-request server restart)
# ---------------------------------------------------------------------------

class TestRemoteEngineReconnect:
    def test_without_policy_poison_is_permanent(self):
        """The pre-retry pin: the proxy stays dead even after a new tier
        appears on the same port — recovery is the parent's problem."""
        eng = FakeEngine("manual")
        tier = ServingTier([eng], monitor_interval_s=0.05)
        tier.start()
        port = tier.port
        rem = RemoteEngine("127.0.0.1", port)
        f = rem.submit("score", [0, 0, 0, 0], seed=1)
        wait_until(lambda: eng.submitted == 1, msg="request routed")
        tier.stop(timeout_s=5)
        # mid-request restart: the in-flight future resolves (drain result,
        # or the typed unavailable), never silence
        wait_until(f.done, msg="future resolution on restart")
        assert f.exception() is None or \
            isinstance(f.exception(), ReplicaUnavailable)
        wait_until(lambda: rem._dead is not None, msg="proxy poisoning")
        tier2 = ServingTier([FakeEngine("auto")], port=port,
                            monitor_interval_s=0.05)
        tier2.start()
        try:
            with pytest.raises(ReplicaUnavailable):
                rem.submit("score", [0, 0, 0, 0], seed=2)
        finally:
            rem.close()
            tier2.stop(timeout_s=5)

    def test_with_policy_recovers_on_fresh_connection(self):
        """The retry layer's pin: a poisoned proxy re-dials on the next
        submit — exactly what a parent router's warm probe performs — and
        serves from the restarted tier."""
        eng = FakeEngine("manual")
        tier = ServingTier([eng], monitor_interval_s=0.05)
        tier.start()
        port = tier.port
        rem = RemoteEngine("127.0.0.1", port, retry=_policy())
        f = rem.submit("score", [1, 1, 1, 1], seed=3)
        wait_until(lambda: eng.submitted == 1, msg="request routed")
        tier.stop(timeout_s=5)
        wait_until(f.done, msg="in-flight future resolves typed")
        wait_until(lambda: rem._dead is not None, msg="proxy poisoning")
        # while the port is vacant, reconnects fail typed (and are
        # backoff-limited — the parent sees unavailable, not a hang)
        with pytest.raises(ReplicaUnavailable):
            rem.submit("score", [0, 0, 0, 0], seed=4)
        tier2 = ServingTier([FakeEngine("auto")], port=port,
                            monitor_interval_s=0.05)
        tier2.start()
        try:
            deadline = time.monotonic() + 10.0
            while True:
                try:
                    f2 = rem.submit("score", [2, 0, 0, 0], seed=7)
                    break
                except ReplicaUnavailable:
                    assert time.monotonic() < deadline, \
                        "proxy never reconnected to the restarted tier"
                    time.sleep(0.02)
            assert f2.result(timeout=5) == 7002.0
            assert rem.reconnects == 1
        finally:
            rem.close()
            tier2.stop(timeout_s=5)

    def test_close_is_final_even_with_policy(self):
        tier = ServingTier([FakeEngine("auto")], monitor_interval_s=0.05)
        tier.start()
        try:
            rem = RemoteEngine("127.0.0.1", tier.port, retry=_policy())
            rem.close()
            with pytest.raises(ReplicaUnavailable):
                rem.submit("score", [0, 0, 0, 0])
        finally:
            tier.stop(timeout_s=5)


# ---------------------------------------------------------------------------
# preemption guard
# ---------------------------------------------------------------------------

class TestPreemptionGuard:
    def test_absorbs_sigterm_and_restores_handler(self):
        before = signal.getsignal(signal.SIGTERM)
        with PreemptionGuard() as g:
            assert not g.requested
            signal.raise_signal(signal.SIGTERM)
            assert g.requested and g.signum == signal.SIGTERM
        assert signal.getsignal(signal.SIGTERM) is before

    def test_inert_off_main_thread(self):
        out = {}

        def worker():
            with PreemptionGuard() as g:
                out["requested"] = g.requested

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert out == {"requested": False}

    def test_sigterm_on_final_pass_finishes_the_stage(self, tmp_path):
        """A signal on a stage's FINAL pass boundary lets the stage finish
        its eval + end-of-stage save before raising — the stage's metrics
        row must exist (skipping it would lose the row in BOTH the
        preempted and the resumed run)."""
        from test_experiment import tiny_config

        from iwae_replication_project_tpu.experiment import (
            TrainingPreempted, run_experiment)

        cfg = tiny_config(tmp_path, n_stages=2, save_figures=False)
        sched = FaultSchedule([FaultRule(
            site=faults.SITE_TRAIN_PASS, action=faults.sigterm(), times=1,
            match=lambda ctx: ctx.get("stage") == 1
            and ctx.get("done") == 1)])   # stage 1 trains exactly 1 pass
        with faults.installed(sched):
            with pytest.raises(TrainingPreempted) as ei:
                run_experiment(cfg, max_batches_per_pass=2, eval_subset=16)
        assert ei.value.stage == 1
        path = os.path.join(cfg.log_dir, cfg.run_name(), "metrics.jsonl")
        assert os.path.exists(path), "preempted stage lost its metrics row"
        state, history = run_experiment(cfg, max_batches_per_pass=2,
                                        eval_subset=16)
        assert len(history) == 1 and history[0][0]["stage"] == 2

    def test_driver_grace_saves_and_resumes(self, tmp_path):
        """Fast end-to-end: a sigterm action at a chosen pass is absorbed,
        TrainingPreempted carries the save point, and the resumed run
        continues at the NEXT pass (full bitwise parity incl. checkpoint
        truncation is the chaos smoke's standing proof)."""
        from test_experiment import tiny_config

        from iwae_replication_project_tpu.experiment import (
            TrainingPreempted, run_experiment)

        cfg = tiny_config(tmp_path, n_stages=2, save_figures=False)
        sched = FaultSchedule([FaultRule(
            site=faults.SITE_TRAIN_PASS, action=faults.sigterm(), times=1,
            match=lambda ctx: ctx.get("stage") == 2
            and ctx.get("done") == 1)])
        with faults.installed(sched):
            with pytest.raises(TrainingPreempted) as ei:
                run_experiment(cfg, max_batches_per_pass=2, eval_subset=16)
        assert ei.value.stage == 2 and ei.value.passes_done == 1
        assert signal.getsignal(signal.SIGTERM) is signal.SIG_DFL or \
            signal.getsignal(signal.SIGTERM) is not None  # restored
        import io
        from contextlib import redirect_stdout
        buf = io.StringIO()
        with redirect_stdout(buf):
            state, history = run_experiment(cfg, max_batches_per_pass=2,
                                            eval_subset=16)
        assert "stage 2, pass 2" in buf.getvalue()
        assert len(history) == 1 and history[0][0]["stage"] == 2


# ---------------------------------------------------------------------------
# checkpoint integrity
# ---------------------------------------------------------------------------

@pytest.fixture
def tiny_state():
    import jax

    from iwae_replication_project_tpu.models.iwae import ModelConfig
    from iwae_replication_project_tpu.training import (
        create_train_state, make_adam)

    cfg = ModelConfig(x_dim=8, n_hidden_enc=(4,), n_latent_enc=(2,),
                      n_hidden_dec=(4,), n_latent_dec=(8,))
    return create_train_state(jax.random.PRNGKey(0), cfg,
                              optimizer=make_adam())


class TestCheckpointIntegrity:
    def test_manifest_written_verified_and_pruned(self, tmp_path, tiny_state):
        from iwae_replication_project_tpu.utils import checkpoint as ck

        d = str(tmp_path / "ckpt")
        for step in (1, 2, 3, 4):
            ck.save_checkpoint(d, step, tiny_state, stage=1, keep=3)
        # retention keeps 3 steps; manifests mirror retention exactly
        assert ck.checkpoint_steps(d) == [4, 3, 2]
        mdir = tmp_path / "ckpt" / "manifests"
        assert sorted(p.name for p in mdir.glob("*.json")) == \
            ["2.json", "3.json", "4.json"]
        for step in (2, 3, 4):
            assert ck.verify_checkpoint(d, step) is None

    def test_truncation_detected_and_fallback_restores(self, tmp_path,
                                                       tiny_state, capsys):
        import jax

        from iwae_replication_project_tpu.utils import checkpoint as ck

        d = str(tmp_path / "ckpt")
        ck.save_checkpoint(d, 1, tiny_state, stage=1, keep=3)
        ck.save_checkpoint(d, 2, tiny_state, stage=2, keep=3)
        path = ck.truncate_newest_checkpoint(d)
        assert path is not None and str(tmp_path) in path
        problem = ck.verify_checkpoint(d, 2)
        assert problem is not None and "mismatch" in problem
        assert ck.verify_checkpoint(d, 1) is None
        restored = ck.restore_latest(d, tiny_state)
        assert restored is not None
        step, state, stage, passes_done = restored
        assert step == 1 and stage == 1     # fell back to the intact step
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)), state.params, tiny_state.params)
        out = capsys.readouterr()
        assert "failed integrity verification" in out.out
        assert "failed integrity verification" in out.err

    def test_all_corrupt_returns_none(self, tmp_path, tiny_state, capsys):
        from iwae_replication_project_tpu.utils import checkpoint as ck

        d = str(tmp_path / "ckpt")
        ck.save_checkpoint(d, 1, tiny_state, stage=1, keep=3)
        ck.truncate_newest_checkpoint(d)
        assert ck.restore_latest(d, tiny_state) is None
        assert "falling back" in capsys.readouterr().out

    def test_pre_manifest_checkpoint_still_restores(self, tmp_path,
                                                    tiny_state):
        """Checkpoints from before this PR have no manifest: verification
        is vacuous and restore proceeds exactly as before."""
        import shutil

        from iwae_replication_project_tpu.utils import checkpoint as ck

        d = str(tmp_path / "ckpt")
        ck.save_checkpoint(d, 5, tiny_state, stage=2, keep=3)
        shutil.rmtree(os.path.join(d, "manifests"))
        assert ck.verify_checkpoint(d, 5) is None
        restored = ck.restore_latest(d, tiny_state)
        assert restored is not None and restored[0] == 5

    def test_config_mismatch_still_raises_not_falls_back(self, tmp_path,
                                                         tiny_state):
        """An intact checkpoint of the WRONG experiment must refuse, never
        quietly fall back past it (run-dir collision protection)."""
        from iwae_replication_project_tpu.utils import checkpoint as ck
        from iwae_replication_project_tpu.utils.config import (
            ExperimentConfig)

        d = str(tmp_path / "ckpt")
        stored = ExperimentConfig(k=7)
        ck.save_checkpoint(d, 1, tiny_state, stage=1, keep=3,
                           config_json=stored.to_json())
        with pytest.raises(ck.CheckpointConfigMismatch):
            ck.restore_latest(d, tiny_state,
                              expect_config_json=ExperimentConfig(
                                  k=13).to_json())

    def test_chaos_truncate_action_composes(self, tmp_path, tiny_state):
        """The schedule-driven corruption path: a rule at the ckpt-save
        site truncates the step it just wrote (the kill-mid-write model)."""
        from iwae_replication_project_tpu.utils import checkpoint as ck

        d = str(tmp_path / "ckpt")
        sched = FaultSchedule([FaultRule(
            site=faults.SITE_CKPT_SAVE, after=1, times=1,
            action=faults.call(
                lambda fc: ck.truncate_newest_checkpoint(
                    fc.ctx["directory"])))])
        with faults.installed(sched):
            ck.save_checkpoint(d, 1, tiny_state, stage=1, keep=3)
            ck.save_checkpoint(d, 2, tiny_state, stage=1, keep=3)
        assert sched.fired() == 1
        assert ck.verify_checkpoint(d, 2) is not None
        assert ck.verify_checkpoint(d, 1) is None
        assert ck.restore_latest(d, tiny_state)[0] == 1
