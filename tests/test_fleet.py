"""Elastic-fleet tests: the autoscale decision function (determinism,
hysteresis, cooldowns, bounds, dry-run), the cost-model placement planner,
signal snapshots (local and wire-shaped), store model-pins, and the
FleetManager lifecycle against fake engines — scale-up joins, drain-based
scale-down, a replica killed mid-scale-event, and the bitwise-parity
guarantee that a fleet which changed shape returns exactly what a static
fleet would for the same admission order.

Device-free throughout (the fake-engine idiom of tests/test_frontend.py):
the controller/planner are pure functions, and the router's dynamic-shape
machinery is exercised with manual-completion fakes at fake-clock speed.
"""

import threading
import time
from concurrent.futures import Future

import pytest

from iwae_replication_project_tpu.serving.batcher import EngineOverloaded
from iwae_replication_project_tpu.serving.fleet import (
    AutoscaleConfig,
    AutoscaleController,
    FleetManager,
    PlacementPlan,
    SignalSnapshot,
    choose_victim,
    local_signals,
    plan_placement,
    wire_signals,
)
from iwae_replication_project_tpu.serving.frontend import (
    ReplicaRouter,
    ServingTier,
)
from iwae_replication_project_tpu.telemetry.slo import (
    SLOMonitor,
    SLOObjective,
    peak_burns,
    window_requests,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class FakeEngine:
    """Minimal engine surface (see tests/test_frontend.py): seed-dependent
    values make reroute/parity checks exact."""

    def __init__(self, mode="auto", dims=4, model=None, k_max=None,
                 sharded=False):
        self.mode = mode
        self.row_dims = {"score": dims, "encode": dims, "decode": dims}
        self.k = 5
        self.lock = threading.Lock()
        self.held = []
        self.submitted = 0
        self.stopped = False
        if model is not None:
            self.model = model
            self.models = (model,)
        if k_max is not None:
            self.k_max = k_max
        if sharded:
            self.sharded = True

    @staticmethod
    def value(row, seed):
        return float(seed) * 1000.0 + float(sum(row))

    def submit(self, op, row, k=None, *, seed=None, model=None):
        with self.lock:
            if self.mode == "shed":
                raise EngineOverloaded("queue full")
            if self.mode == "raise":
                raise RuntimeError("device on fire")
            self.submitted += 1
            f = Future()
            if self.mode == "manual":
                self.held.append((op, list(row), k, seed, f))
            else:
                f.set_result(self.value(row, seed))
            return f

    def finish(self, n=None, exc=None):
        with self.lock:
            batch, self.held = (self.held[:n], self.held[n:]) if n else \
                (self.held, [])
        for _, row, _, seed, f in batch:
            try:
                if exc is not None:
                    f.set_exception(exc)
                else:
                    f.set_result(self.value(row, seed))
            except Exception:
                pass
        return len(batch)

    def start(self):
        pass

    def stop(self, timeout_s=None):
        self.stopped = True
        self.finish()

    def warmup(self, ops=(), ks=None):
        return {"programs": 0.0}


class CrashOnStopEngine(FakeEngine):
    """A replica that dies exactly when the drain asks it to flush — the
    mid-scale-event kill."""

    def stop(self, timeout_s=None):
        self.stopped = True
        raise RuntimeError("replica killed mid-scale-event")


def wait_until(pred, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.002)


def snap(t=0.0, replicas=2, fast=0.0, slow=0.0, outstanding=0,
         indices=None, inflight=None, requests=0):
    idx = tuple(range(replicas)) if indices is None else tuple(indices)
    return SignalSnapshot(
        t=t, replicas=replicas, draining=0, unhealthy=0,
        outstanding=outstanding, burns={"5m": fast, "1h": slow},
        requests={"5m": requests}, store={}, live_indices=idx,
        inflight=tuple([0] * len(idx)) if inflight is None
        else tuple(inflight))


# ---------------------------------------------------------------------------
# controller: rules, hysteresis, cooldowns, determinism
# ---------------------------------------------------------------------------

def test_scale_up_on_confirmed_burn_breach():
    c = AutoscaleController(AutoscaleConfig(max_replicas=4,
                                            confirm_burn=0.5))
    d = c.decide(snap(t=0, replicas=2, fast=2.0, slow=1.0))
    assert d.action == "up" and d.target == 3 and d.rule == "burn-breach"


def test_scale_up_needs_slow_window_confirmation():
    c = AutoscaleController(AutoscaleConfig(confirm_burn=0.5))
    # a 5m spike the 1h window does not confirm holds (multi-window guard)
    d = c.decide(snap(t=0, replicas=2, fast=5.0, slow=0.1))
    assert d.action == "hold" and d.rule == "in-band"


def test_scale_up_bounded_and_cooled_down():
    c = AutoscaleController(AutoscaleConfig(max_replicas=4,
                                            up_cooldown_s=30.0))
    assert c.decide(snap(t=0, replicas=2, fast=2.0, slow=2.0)).action == "up"
    # breach persists inside the cooldown: hold, with the rule named
    d = c.decide(snap(t=10, replicas=3, fast=2.0, slow=2.0))
    assert d.action == "hold" and d.rule == "up-cooldown"
    # cooldown passed: grow again — then the bound caps further growth
    # (at-max outranks cooldown in the rule order)
    assert c.decide(snap(t=50, replicas=3, fast=2.0, slow=2.0)).action == "up"
    d = c.decide(snap(t=60, replicas=4, fast=2.0, slow=2.0))
    assert d.action == "hold" and d.rule == "at-max"


def test_scale_down_when_idle_after_cooldown():
    c = AutoscaleController(AutoscaleConfig(min_replicas=1,
                                            down_cooldown_s=60.0))
    d = c.decide(snap(t=0, replicas=3, fast=0.0, outstanding=0,
                      indices=(0, 1, 5), inflight=(0, 0, 0)))
    # no prior scale event: idle shrinks immediately, draining the
    # youngest (highest stable index) among the equally-loaded
    assert d.action == "down" and d.target == 2 and d.victim == 5
    # within down-cooldown of that event: hold
    d2 = c.decide(snap(t=30, replicas=2, fast=0.0))
    assert d2.action == "hold" and d2.rule == "down-cooldown"
    # past it: shrink again, to the floor
    d3 = c.decide(snap(t=100, replicas=2, fast=0.0))
    assert d3.action == "down" and d3.target == 1
    d4 = c.decide(snap(t=300, replicas=1, fast=0.0))
    assert d4.action == "hold" and d4.rule == "at-min"


def test_no_scale_down_with_work_in_flight():
    c = AutoscaleController(AutoscaleConfig())
    d = c.decide(snap(t=0, replicas=3, fast=0.0, outstanding=4))
    assert d.action == "hold"


def test_hysteresis_band_holds():
    cfg = AutoscaleConfig(scale_up_burn=1.0, scale_down_burn=0.25)
    c = AutoscaleController(cfg)
    d = c.decide(snap(t=0, replicas=2, fast=0.6))
    assert d.action == "hold" and d.rule == "in-band"


def test_down_cooldown_measured_from_scale_up_too():
    """A fresh scale-up is never immediately unwound by an idle tick."""
    c = AutoscaleController(AutoscaleConfig(down_cooldown_s=60.0))
    assert c.decide(snap(t=0, replicas=2, fast=2.0, slow=2.0)).action == "up"
    d = c.decide(snap(t=10, replicas=3, fast=0.0))
    assert d.action == "hold" and d.rule == "down-cooldown"


def test_dry_run_decides_but_never_arms_cooldowns():
    c = AutoscaleController(AutoscaleConfig(dry_run=True,
                                            up_cooldown_s=1e9))
    d1 = c.decide(snap(t=0, replicas=2, fast=2.0, slow=2.0))
    assert d1.action == "up" and d1.dry_run
    # nothing was actuated, so the (huge) cooldown must not have started:
    # the identical breach still reads as an "up" decision
    d2 = c.decide(snap(t=1, replicas=2, fast=2.0, slow=2.0))
    assert d2.action == "up" and d2.dry_run


def test_decision_sequence_is_deterministic():
    snaps = [snap(t=float(i * 10), replicas=2 + (i % 2),
                  fast=(2.0 if i % 3 == 0 else 0.0),
                  slow=(2.0 if i % 3 == 0 else 0.0)) for i in range(12)]
    logs = []
    for _ in range(2):
        c = AutoscaleController(AutoscaleConfig(seed=7))
        for s in snaps:
            c.decide(s)
        logs.append(c.log)
    assert logs[0] == logs[1]
    # every record carries the inputs it was a function of
    assert all("inputs" in rec and "rule" in rec for rec in logs[0])


def test_decision_log_and_fleet_metrics_published():
    c = AutoscaleController(AutoscaleConfig())
    c.decide(snap(t=0, replicas=2, fast=2.0, slow=2.0))
    c.decide(snap(t=100, replicas=3, fast=0.5))
    assert [r["action"] for r in c.log] == ["up", "hold"]
    assert c.registry.counter("fleet/decisions").value == 2
    assert c.registry.counter("fleet/scale_ups").value == 1
    assert c.registry.gauge("fleet/burn_fast").value == 0.5


def test_zero_live_replicas_holds():
    c = AutoscaleController(AutoscaleConfig())
    d = c.decide(snap(t=0, replicas=0, fast=9.0, slow=9.0, indices=()))
    assert d.action == "hold" and d.rule == "no-live-replicas"


def test_config_validation():
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=0)
    with pytest.raises(ValueError):
        AutoscaleConfig(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        AutoscaleConfig(scale_up_burn=0.5, scale_down_burn=1.0)
    with pytest.raises(ValueError):
        AutoscaleConfig(up_cooldown_s=-1)


def test_choose_victim_least_loaded_youngest_seeded():
    assert choose_victim([0, 1, 2], [3, 1, 2]) == 1
    # tie on load: youngest (highest index) first
    assert choose_victim([0, 1, 2], [1, 0, 0]) == 2
    # the seed rotates only among tied candidates, deterministically
    assert choose_victim([0, 1, 2], [1, 0, 0], seed=1) == 1
    assert choose_victim([0, 1, 2], [1, 0, 0], seed=2) == 2
    assert choose_victim([], []) is None


# ---------------------------------------------------------------------------
# planner: deterministic first-fit-decreasing placement
# ---------------------------------------------------------------------------

def test_plan_placement_first_fit_decreasing():
    plan = plan_placement({"a": 100, "b": 50, "c": 300},
                          {0: 200, 1: 320})
    # c (largest) lands first; with seed 0 replicas are visited 0, 1 —
    # c overflows 0's budget onto 1; a then b fill 0
    assert plan.assignments == ((0, ("a", "b")), (1, ("c",)))
    assert plan.overflow == ()
    assert plan.home_of("c") == 1 and plan.home_of("a") == 0


def test_plan_placement_is_deterministic_and_seed_rotates():
    args = ({"a": 100, "b": 100}, {3: 1000, 7: 1000})
    assert plan_placement(*args) == plan_placement(*args)
    p0 = plan_placement(*args, seed=0)
    p1 = plan_placement(*args, seed=1)
    # same models placed; the seed only rotates which replica is first-fit
    assert p0.placed() == p1.placed() == ("a", "b")
    assert p0.models_for(3) == ("a", "b") and p1.models_for(7) == ("a", "b")


def test_plan_placement_overflow_and_unbounded():
    plan = plan_placement({"big": 10_000, "small": 10}, {0: 100})
    assert plan.overflow == ("big",) and plan.models_for(0) == ("small",)
    # an unbounded budget (None) takes everything
    plan = plan_placement({"big": 10_000, "small": 10}, {0: None})
    assert plan.overflow == () and plan.models_for(0) == ("big", "small")


def test_plan_placement_respects_replica_capabilities():
    plan = plan_placement(
        {"a": 10, "b": 10}, {0: 1000, 1: 1000},
        replica_models={0: frozenset({"a"}), 1: frozenset({"b"})})
    assert plan.models_for(0) == ("a",) and plan.models_for(1) == ("b",)
    # a model NO replica may host overflows rather than landing wrong
    plan = plan_placement({"c": 10}, {0: 1000},
                          replica_models={0: frozenset({"a"})})
    assert plan.overflow == ("c",)


def test_plan_record_shape():
    rec = plan_placement({"a": 5}, {0: 10}).record()
    assert rec == {"assignments": [[0, ["a"]]], "overflow": [],
                   "costs": {"a": 5}}


# ---------------------------------------------------------------------------
# signals: one snapshot schema, local and wire
# ---------------------------------------------------------------------------

def _burn_doc(fast_burn, requests=10):
    return {"m/score": {"objective": {}, "windows": {
        "5m": {"requests": requests, "latency_burn": fast_burn,
               "availability_burn": 0.0},
        "1h": {"requests": requests, "latency_burn": fast_burn / 2,
               "availability_burn": 0.0}}}}


def test_peak_burns_and_window_requests_reductions():
    doc = dict(_burn_doc(2.0), **{"n/score": {"windows": {
        "5m": {"requests": 3, "latency_burn": 0.1,
               "availability_burn": 4.0}}}})
    assert peak_burns(doc) == {"5m": 4.0, "1h": 1.0}
    assert window_requests(doc) == {"5m": 13, "1h": 10}
    assert peak_burns({}) == {} and window_requests({}) == {}


def test_local_signals_snapshot():
    clock = FakeClock(100.0)
    engines = [FakeEngine("manual"), FakeEngine("auto")]
    router = ReplicaRouter(engines, clock=clock)
    slo = SLOMonitor(registry=router.registry, clock=clock,
                     default=SLOObjective(latency_s=0.01))

    class StubTier:
        pass

    tier = StubTier()
    tier.router, tier.slo, tier.clock = router, slo, clock
    router.submit("score", [0, 0, 0, 0])          # held on the manual fake
    slo.observe("score", 5.0, model="m")          # a latency violation
    s = local_signals(tier)
    assert s.t == 100.0 and s.replicas == 2 and s.outstanding == 1
    assert s.live_indices == (0, 1) and s.inflight == (1, 0)
    assert s.burn("5m") > 1.0                     # 100% violations burn hot
    assert s.requests_in("5m") == 1
    engines[0].finish()
    router.drain(timeout_s=5)


def test_wire_signals_matches_local_reduction():
    """The fleet-of-fleets contract: the `slo` wire doc reduces to the
    same snapshot numbers a local monitor would."""
    states = [{"index": 0, "healthy": True, "draining": False,
               "inflight": 0}]
    doc = {"enabled": True, "slo": _burn_doc(3.0)}
    s = wire_signals(doc, replica_states=states, t=5.0)
    assert s.burn("5m") == 3.0 and s.burn("1h") == 1.5
    assert s.replicas == 1 and s.t == 5.0
    # the raw snapshot shape (no envelope) is accepted too
    s2 = wire_signals(_burn_doc(3.0), replica_states=states, t=5.0)
    assert s2.burns == s.burns
    # disabled child: zero burns, not a crash
    s3 = wire_signals({"enabled": False, "slo": {}},
                      replica_states=states, t=5.0)
    assert s3.burns == {}


# ---------------------------------------------------------------------------
# store: model-level placement pins
# ---------------------------------------------------------------------------

def test_store_model_pins_block_eviction_until_release():
    import numpy as np

    from iwae_replication_project_tpu.utils.compile_cache import (
        ExecutableStore)
    import jax

    store = ExecutableStore(budget_bytes=None)
    fn = jax.jit(lambda x: x + 1)
    for i, model in enumerate(("hot", "cold")):
        store.get_or_compile(f"prog{i}", fn,
                             (np.arange(4 + i, dtype=np.float32),), {},
                             None, ("bk",), True, model=model)
    costs = store.model_costs()
    assert set(costs) == {"hot", "cold"} and all(
        c > 0 for c in costs.values())
    pin = store.pin_model("hot")
    assert store.model_pins() == {"hot": 1}
    store.set_budget(0)          # evict everything unpinned
    assert [e["model"] for e in store.entries()] == ["hot"]
    assert store.stats()["model_pins"] == {"hot": 1}
    pin.release()
    assert store.model_pins() == {}
    store.set_budget(0)
    assert store.entries() == []
    pin.release()                # double release is a no-op


# ---------------------------------------------------------------------------
# lifecycle: FleetManager over fakes
# ---------------------------------------------------------------------------

class StubStore:
    """The store surface FleetManager's planner path consumes."""

    def __init__(self, costs=None, budget=None):
        self.costs = dict(costs or {})
        self.budget_bytes = budget
        self.pins = []

    def model_costs(self):
        return dict(self.costs)

    def pin_model(self, model):
        class Pin:
            def __init__(p, s, m):
                p.s, p.model = s, m
                s.pins.append(p)

            def release(p):
                p.s.pins.remove(p)
        return Pin(self, model)


def make_manager(n=2, config=None, factory_engines=None, costs=None,
                 clock=None, model=None):
    clock = clock if clock is not None else FakeClock()
    engines = [FakeEngine("auto", model=model) for _ in range(n)]
    router = ReplicaRouter(engines, clock=clock)
    slo = SLOMonitor(registry=router.registry, clock=clock,
                     default=SLOObjective(latency_s=0.01))

    class StubTier:
        pass

    tier = StubTier()
    tier.router, tier.slo, tier.clock = router, slo, clock
    made = list(factory_engines or [])

    def factory():
        return made.pop(0) if made else FakeEngine("auto", model=model)

    mgr = FleetManager(
        tier, factory,
        config or AutoscaleConfig(min_replicas=1, max_replicas=4,
                                  up_cooldown_s=0.0, down_cooldown_s=0.0),
        store=StubStore(costs), warm_join=True, clock=clock)
    return mgr, engines, slo, clock


def test_manager_scales_up_on_breach_and_down_when_idle():
    mgr, engines, slo, clock = make_manager(n=2)
    # burn the budget: slow requests against a 10ms objective
    for _ in range(5):
        slo.observe("score", 1.0)
    clock.t = 10.0
    d = mgr.step()
    assert d.action == "up"
    assert len(mgr.router.engines) == 3
    assert mgr.decision_log[-1]["action"] == "up"
    # placement ran on the shape change
    assert mgr.placement_log and \
        mgr.placement_log[-1]["cause"] == "scale-up"
    # idle: the burn-rate windows rotate past the violations
    clock.t = 5000.0
    d = mgr.step()
    assert d.action == "down"
    assert len(mgr.router.engines) == 2
    # the drained engine was stopped and retained for teardown
    assert len(mgr.retired) == 1 and mgr.retired[0].stopped


def test_manager_dry_run_never_actuates():
    cfg = AutoscaleConfig(dry_run=True, up_cooldown_s=0.0,
                          down_cooldown_s=0.0)
    mgr, engines, slo, clock = make_manager(n=2, config=cfg)
    for _ in range(5):
        slo.observe("score", 1.0)
    clock.t = 10.0
    d = mgr.step()
    assert d.action == "up" and d.dry_run
    assert len(mgr.router.engines) == 2          # untouched
    assert mgr.decision_log[-1]["dry_run"]


def test_manager_warm_join_warms_before_exposure():
    warmed = []

    class WarmupProbe(FakeEngine):
        def warmup(self, ops=(), ks=None):
            warmed.append(len(self.held))
            return {}

    mgr, _, slo, clock = make_manager(factory_engines=[WarmupProbe("auto")])
    mgr.scale_up()
    # warmup ran exactly once, before any routed traffic reached it
    assert warmed == [0]


def test_manager_survives_replica_killed_mid_scale_event():
    """The chaos pin: the scale-down victim dies during its drain flush;
    its in-flight work reroutes with original seeds and the removal
    completes — no lost requests, no stuck manager."""
    victim = CrashOnStopEngine("manual")
    peer = FakeEngine("auto")
    clock = FakeClock()
    router = ReplicaRouter([victim, peer], clock=clock)
    slo = SLOMonitor(registry=router.registry, clock=clock)

    class StubTier:
        pass

    tier = StubTier()
    tier.router, tier.slo, tier.clock = router, slo, clock
    mgr = FleetManager(tier, FakeEngine, AutoscaleConfig(),
                       store=StubStore(), clock=clock)
    # park work on the victim (it serves (score, k=1) first by index order)
    futs = [router.submit("score", [float(i), 0, 0, 0], k=1)
            for i in range(4)]
    assert victim.held
    assert mgr.scale_down(victim=0) == 0
    # every accepted request resolved, with its ORIGINAL admission seed
    got = [f.result(timeout=5) for f in futs]
    assert got == [i * 1000.0 + float(i) for i in range(4)]
    assert len(router.engines) == 1
    assert router.registry.counter("router/reroutes").value >= 1


def test_manager_rebalance_pins_and_primes_affinity():
    mgr, engines, slo, clock = make_manager(
        n=2, costs={"m1": 100, "m2": 50}, model=None)
    plan = mgr.rebalance()
    assert isinstance(plan, PlacementPlan)
    assert sorted(p.model for p in mgr.store.pins) == ["m1", "m2"]
    # a re-plan swaps pins, never leaks them
    mgr.rebalance()
    assert sorted(p.model for p in mgr.store.pins) == ["m1", "m2"]
    rec = mgr.placement_log[-1]
    assert rec["event"] == "rebalance" and rec["costs"] == {"m1": 100,
                                                            "m2": 50}


def test_manager_control_thread_runs_and_stops():
    cfg = AutoscaleConfig(interval_s=0.01)
    mgr, engines, slo, clock = make_manager(n=2, config=cfg)
    mgr.start()
    try:
        wait_until(lambda: len(mgr.decision_log) >= 3,
                   msg="control loop ticks")
    finally:
        mgr.stop()
    n = len(mgr.decision_log)
    time.sleep(0.05)
    assert len(mgr.decision_log) == n            # the loop actually stopped


# ---------------------------------------------------------------------------
# the scale-event parity pin: elastic fleet == static fleet, bitwise
# ---------------------------------------------------------------------------

def test_scale_events_preserve_admission_order_results():
    """Grow mid-burst, shrink mid-burst: results are exactly what a static
    single-replica fleet returns for the same admission order, because
    seeds are minted at admission — fleet shape never touches them."""
    rows = [[float(i), 1.0, 0, 0] for i in range(18)]

    # reference: a static 1-replica fleet, same admission order
    static = ReplicaRouter([FakeEngine("auto")])
    ref = [static.submit("score", r).result(timeout=5) for r in rows]
    static.drain(timeout_s=5)

    clock = FakeClock()
    e0 = FakeEngine("auto")
    router = ReplicaRouter([e0], clock=clock)
    slo = SLOMonitor(registry=router.registry, clock=clock)

    class StubTier:
        pass

    tier = StubTier()
    tier.router, tier.slo, tier.clock = router, slo, clock
    mgr = FleetManager(tier, FakeEngine, AutoscaleConfig(),
                       store=StubStore(), clock=clock)
    got = []
    for i, r in enumerate(rows):
        if i == 6:
            mgr.scale_up()                      # grow 1 -> 2 mid-burst
        if i == 12:
            mgr.scale_down(victim=1)            # shrink back mid-burst
        got.append(router.submit("score", r).result(timeout=5))
    assert got == ref
    router.drain(timeout_s=5)


def test_scale_down_under_load_real_sockets_parity():
    """Satellite: drain-based removal with in-flight work over real
    sockets — every accepted request resolves ok, results bitwise equal to
    a static fleet with the same admission order."""
    from iwae_replication_project_tpu.serving.frontend import TierClient

    # static reference fleet (1 replica), same admission order
    static = ServingTier([FakeEngine("auto")], monitor_interval_s=0.05)
    static.start()
    try:
        with TierClient("127.0.0.1", static.port) as c:
            ref = [c.score([[float(i), 0, 0, 0]])[0] for i in range(10)]
    finally:
        static.stop(timeout_s=10)

    doomed, keeper = FakeEngine("manual"), FakeEngine("auto")
    tier = ServingTier([doomed, keeper], monitor_interval_s=0.05)
    tier.start()
    try:
        with TierClient("127.0.0.1", tier.port) as c:
            ids = [c.submit("score", [[float(i), 0, 0, 0]], k=(i % 2) + 1)
                   for i in range(6)]
            wait_until(lambda: doomed.submitted + keeper.submitted == 6,
                       msg="burst routed")
            assert doomed.held                   # in-flight work to drain
            remover = threading.Thread(
                target=lambda: tier.router.remove_replica(0, timeout_s=10),
                daemon=True)
            remover.start()                      # FakeEngine.stop completes
            remover.join(timeout=10)             # the held futures
            assert not remover.is_alive()
            ids += [c.submit("score", [[float(i), 0, 0, 0]])
                    for i in range(6, 10)]
            done = c.drain(ids)
        assert len(done) == 10
        got = [done[rid]["result"][0] for rid in ids]
        assert all(done[rid]["ok"] for rid in ids)
        assert got == ref
        assert len(tier.router.engines) == 1
    finally:
        tier.stop(timeout_s=10)
