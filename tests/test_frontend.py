"""Serving-tier tests: router policy (fake engines, no device), quota state
machine (fake clock), wire protocol, the TCP front end over real sockets,
RemoteEngine fleet composition, and the real-engine integration pins (fleet
vs direct-engine bitwise parity; multi-client stream causes zero recompiles).

The router/quota/protocol layers are deliberately device-free: everything
with the engine surface (``submit(op, row, k=, seed=)`` -> Future, ``stop``,
``row_dims``, ``k``) routes, so the whole failure model — reroute, stall
drain, probe re-admission, graceful drain — is pinned with fakes at
fake-clock speed. Only the two integration tests at the bottom build real
(tiny) engines.
"""

import json
import threading
import time
from concurrent.futures import Future

import numpy as np
import pytest

from iwae_replication_project_tpu.serving.batcher import (
    EngineOverloaded,
    RequestTimeout,
)
from iwae_replication_project_tpu.serving.frontend import (
    ClientQuotas,
    QuotaExceeded,
    QuotaPolicy,
    RemoteEngine,
    ReplicaRouter,
    ReplicaUnavailable,
    ServingTier,
    TierClient,
    TierOverloaded,
)
from iwae_replication_project_tpu.serving.frontend import protocol
from iwae_replication_project_tpu.serving.frontend.client import TierError


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


class FakeEngine:
    """The engine surface with scripted behavior and manual completion.

    ``mode``:
      * "auto"  — submits complete immediately with ``seed * 1000 + sum(row)``
                  (seed-dependent so reroute-with-same-seed is checkable);
      * "manual"— futures are held; tests complete them via :meth:`finish`;
      * "shed"  — every submit raises :class:`EngineOverloaded`;
      * "raise" — every submit raises RuntimeError (submit-time failure).
    """

    def __init__(self, mode="auto", dims=4):
        self.mode = mode
        self.row_dims = {"score": dims, "encode": dims, "decode": dims}
        self.k = 5
        self.lock = threading.Lock()
        self.held = []            # (op, row, k, seed, future) in manual mode
        self.submitted = 0
        self.stopped = False

    @staticmethod
    def value(row, seed):
        return float(seed) * 1000.0 + float(sum(row))

    def submit(self, op, row, k=None, *, seed=None):
        with self.lock:
            if self.mode == "shed":
                raise EngineOverloaded("queue full")
            if self.mode == "raise":
                raise RuntimeError("device on fire")
            self.submitted += 1
            f = Future()
            if self.mode == "manual":
                self.held.append((op, list(row), k, seed, f))
            else:
                f.set_result(self.value(row, seed))
            return f

    def finish(self, n=None, exc=None):
        """Complete the first `n` held futures (all by default), each with
        its seed-derived value or `exc`."""
        with self.lock:
            batch, self.held = (self.held[:n], self.held[n:]) if n else \
                (self.held, [])
        for _, row, _, seed, f in batch:
            try:
                if exc is not None:
                    f.set_exception(exc)
                else:
                    f.set_result(self.value(row, seed))
            except Exception:
                pass
        return len(batch)

    def start(self):
        pass

    def stop(self, timeout_s=None):
        self.stopped = True
        self.finish()

    def warmup(self, ops=(), ks=None):
        return {"programs": 0.0}


def wait_until(pred, timeout=5.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while not pred():
        if time.monotonic() > deadline:
            raise AssertionError(f"timed out waiting for {msg}")
        time.sleep(0.002)


# ---------------------------------------------------------------------------
# router selection policy
# ---------------------------------------------------------------------------

def test_least_inflight_tie_break_lowest_index():
    engines = [FakeEngine("manual") for _ in range(3)]
    r = ReplicaRouter(engines, affinity_slack=0)
    # distinct (op, k) per submit so affinity never applies; equal inflight
    # must break to the lowest index each time
    r.submit("score", [1, 1, 1, 1], k=1)
    assert engines[0].submitted == 1
    r.submit("score", [1, 1, 1, 1], k=2)
    assert engines[1].submitted == 1
    r.submit("score", [1, 1, 1, 1], k=3)
    assert engines[2].submitted == 1
    # now 1-1-1 inflight: next goes to index 0 again
    r.submit("score", [1, 1, 1, 1], k=4)
    assert engines[0].submitted == 2
    for e in engines:
        e.finish()
    r.drain(timeout_s=5)


def test_bucket_affinity_sticky_within_slack():
    engines = [FakeEngine("manual") for _ in range(2)]
    r = ReplicaRouter(engines, affinity_slack=2)
    futs = [r.submit("score", [0, 0, 0, 0], k=7) for _ in range(3)]
    # all three (score, 7) requests stick to replica 0: its inflight (1, 2)
    # stays within slack of the idle peer
    assert engines[0].submitted == 3 and engines[1].submitted == 0
    assert r.registry.counter("router/affinity_hits").value >= 2
    engines[0].finish()
    for f in futs:
        assert f.result(timeout=5) == FakeEngine.value([0, 0, 0, 0],
                                                       f_seed(futs, f))
    r.drain(timeout_s=5)


def f_seed(futs, f):
    """Seeds are minted in admission order starting at 0."""
    return futs.index(f)


def test_affinity_overridden_past_slack():
    engines = [FakeEngine("manual") for _ in range(2)]
    r = ReplicaRouter(engines, affinity_slack=1)
    for _ in range(3):
        r.submit("score", [0, 0, 0, 0], k=7)
    # inflight now 2 on replica 0 vs 0 on replica 1: beyond slack 1, the
    # third submit must have overridden affinity to the least-loaded peer
    assert engines[0].submitted == 2 and engines[1].submitted == 1
    for e in engines:
        e.finish()
    r.drain(timeout_s=5)


def test_seed_minting_admission_order_and_explicit_seed():
    eng = FakeEngine("auto")
    r = ReplicaRouter([eng])
    got = [r.submit("score", [0, 0, 0, 0]).result(timeout=5)
           for _ in range(3)]
    assert got == [0.0, 1000.0, 2000.0]       # minted seeds 0, 1, 2
    # an explicit seed rides through untouched and does not advance minting
    assert r.submit("score", [0, 0, 0, 0],
                    seed=77).result(timeout=5) == 77000.0
    assert r.submit("score", [0, 0, 0, 0]).result(timeout=5) == 3000.0
    r.drain(timeout_s=5)


# ---------------------------------------------------------------------------
# admission ceiling + shedding
# ---------------------------------------------------------------------------

def test_tier_ceiling_sheds_typed():
    engines = [FakeEngine("manual")]
    r = ReplicaRouter(engines, max_outstanding=2)
    r.submit("score", [0, 0, 0, 0])
    r.submit("score", [0, 0, 0, 0])
    with pytest.raises(TierOverloaded):
        r.submit("score", [0, 0, 0, 0])
    assert r.registry.counter("router/sheds").value == 1
    engines[0].finish()
    # completions free ceiling slots
    r.submit("score", [0, 0, 0, 0]).cancel()
    r.drain(timeout_s=5)


def test_every_replica_shedding_is_engine_overloaded():
    r = ReplicaRouter([FakeEngine("shed"), FakeEngine("shed")])
    with pytest.raises(EngineOverloaded):
        r.submit("score", [0, 0, 0, 0])
    assert r.outstanding == 0          # the failed admit was retired
    r.drain(timeout_s=5)


def test_submit_shed_walks_to_healthy_peer():
    shed, ok = FakeEngine("shed"), FakeEngine("auto")
    r = ReplicaRouter([shed, ok])
    assert r.submit("score", [1, 0, 0, 0]).result(timeout=5) == 1.0
    states = r.replica_states()
    assert states[0]["healthy"], "a shed is backpressure, not a failure"
    r.drain(timeout_s=5)


# ---------------------------------------------------------------------------
# failure handling: reroute, stall, probe re-admission
# ---------------------------------------------------------------------------

def test_replica_failure_reroutes_zero_lost_futures():
    bad, good = FakeEngine("manual"), FakeEngine("manual")
    r = ReplicaRouter([bad, good], affinity_slack=0)
    # alternate (op, k) groups so both replicas hold work
    futs = [r.submit("score", [1, 1, 1, 1], k=(i % 2) + 1) for i in range(8)]
    assert bad.submitted == 4 and good.submitted == 4
    # replica 0 dies: its oldest future errors, the rest of its work is
    # drained and rerouted to the healthy peer WITH the original seeds
    bad.finish(exc=RuntimeError("XLA runtime poisoned"))
    wait_until(lambda: good.submitted == 8, msg="reroute to healthy peer")
    good.finish()
    for i, f in enumerate(futs):
        assert f.result(timeout=5) == FakeEngine.value([1, 1, 1, 1], i), \
            "rerouted request must return the ORIGINAL seed's result"
    states = r.replica_states()
    assert not states[0]["healthy"] and states[1]["healthy"]
    assert r.registry.counter("router/replica_failures").value == 1
    assert r.registry.counter("router/reroutes").value == 4
    assert r.registry.gauge("router/healthy/r0").value == 0
    r.drain(timeout_s=5)


def test_async_shed_with_no_peer_stays_typed_overloaded():
    """A shed is 'full, not failed' even when there is nowhere to reroute:
    the single-replica (or all-peers-excluded) async-shed path must surface
    the original EngineOverloaded — back off and retry — not a
    ReplicaUnavailable that reads as fleet-down."""
    a = FakeEngine("manual")
    r = ReplicaRouter([a])
    f = r.submit("score", [0, 0, 0, 0])
    a.finish(exc=EngineOverloaded("window saturated"))
    with pytest.raises(EngineOverloaded):
        f.result(timeout=5)
    assert r.replica_states()[0]["healthy"]
    assert r.registry.counter("router/replica_failures").value == 0
    r.drain(timeout_s=5)


def test_async_shed_reroutes_without_marking_dead():
    a, b = FakeEngine("manual"), FakeEngine("manual")
    r = ReplicaRouter([a, b], affinity_slack=0)
    f = r.submit("score", [2, 0, 0, 0])
    assert a.submitted == 1
    # an EngineOverloaded delivered via the future (how remote replicas
    # shed): the replica is full, not failed — retry peers, stay healthy
    a.finish(exc=EngineOverloaded("window saturated"))
    wait_until(lambda: b.submitted == 1, msg="shed reroute")
    b.finish()
    assert f.result(timeout=5) == 2.0
    assert r.replica_states()[0]["healthy"]
    assert r.registry.counter("router/replica_failures").value == 0
    r.drain(timeout_s=5)


def test_request_timeout_is_terminal_no_reroute():
    a, b = FakeEngine("manual"), FakeEngine("manual")
    r = ReplicaRouter([a, b], affinity_slack=0)
    f = r.submit("score", [0, 0, 0, 0])
    a.finish(exc=RequestTimeout("queue deadline passed"))
    with pytest.raises(RequestTimeout):
        f.result(timeout=5)
    assert b.submitted == 0, "expired requests must not be re-served late"
    assert r.replica_states()[0]["healthy"]
    r.drain(timeout_s=5)


def test_stall_detection_drains_wedged_replica():
    clock = FakeClock()
    wedged, ok = FakeEngine("manual"), FakeEngine("manual")
    r = ReplicaRouter([wedged, ok], affinity_slack=0, stall_deadline_s=10.0,
                      clock=clock)
    f = r.submit("score", [3, 0, 0, 0])
    assert wedged.submitted == 1
    clock.t = 5.0
    assert r.check_stalls() == 0, "within deadline: no drain"
    clock.t = 10.1
    assert r.check_stalls() == 1
    wait_until(lambda: ok.submitted == 1, msg="stall reroute")
    ok.finish()
    assert f.result(timeout=5) == 3.0
    assert not r.replica_states()[0]["healthy"]
    assert r.registry.counter("router/stall_drains").value == 1
    r.drain(timeout_s=5)


def test_probe_readmission():
    flaky = FakeEngine("raise")
    ok = FakeEngine("auto")
    r = ReplicaRouter([flaky, ok], probe_timeout_s=1.0)
    # submit-time failure marks r0 unhealthy and lands on r1
    assert r.submit("score", [1, 0, 0, 0]).result(timeout=5) == 1.0
    assert not r.replica_states()[0]["healthy"]
    # while broken, probes fail and it stays out
    assert r.probe_unhealthy() == 0
    assert not r.replica_states()[0]["healthy"]
    # repaired: one successful warm probe re-admits it
    flaky.mode = "auto"
    assert r.probe_unhealthy() == 1
    assert r.replica_states()[0]["healthy"]
    assert r.registry.counter("router/probe_readmits").value == 1
    assert r.registry.gauge("router/healthy/r0").value == 1
    r.drain(timeout_s=5)


def test_drain_on_stop_completes_everything():
    engines = [FakeEngine("manual") for _ in range(2)]
    r = ReplicaRouter(engines, affinity_slack=0)
    futs = [r.submit("score", [1, 1, 1, 1], k=(i % 2) + 1) for i in range(6)]
    # drain: intake closes, engine.stop() flushes held work, all complete
    r.drain(timeout_s=5)
    assert all(e.stopped for e in engines)
    assert all(f.done() for f in futs), "drain lost futures"
    assert sum(1 for f in futs if f.exception() is None) == 6
    with pytest.raises(ReplicaUnavailable):
        r.submit("score", [0, 0, 0, 0])
    assert r.outstanding == 0


def test_drain_error_completes_leftovers():
    class DeadStop(FakeEngine):
        def stop(self, timeout_s=None):   # dies holding work: futures leak
            raise RuntimeError("segfault during drain")

    eng = DeadStop("manual")
    r = ReplicaRouter([eng])
    f = r.submit("score", [0, 0, 0, 0])
    r.drain(timeout_s=1.0)
    # the engine died without completing it; drain must still answer
    assert f.done()
    assert isinstance(f.exception(), ReplicaUnavailable)


# ---------------------------------------------------------------------------
# quota state machine (fake clock)
# ---------------------------------------------------------------------------

def test_quota_refill_and_reject():
    clock = FakeClock()
    q = ClientQuotas(QuotaPolicy(rate=2.0, burst=4.0), clock=clock)
    q.admit("a", 4)                       # full bucket covers the burst
    with pytest.raises(QuotaExceeded):
        q.admit("a", 1)                   # dry
    assert q.tokens("a") == 0.0           # rejection consumed nothing
    clock.t = 1.0                         # refill 2 tokens
    q.admit("a", 2)
    with pytest.raises(QuotaExceeded):
        q.admit("a", 1)
    clock.t = 100.0                       # refill clamps at burst
    assert q.tokens("a") == 4.0
    with pytest.raises(QuotaExceeded):
        q.admit("a", 5)                   # cost > burst can NEVER be admitted


def test_quota_per_client_isolation_and_anonymous():
    clock = FakeClock()
    q = ClientQuotas(QuotaPolicy(rate=1.0, burst=2.0), clock=clock)
    q.admit("a", 2)
    q.admit("b", 2)                       # b's bucket is its own
    with pytest.raises(QuotaExceeded):
        q.admit("a", 1)
    q.admit(None, 2)                      # no client id = shared principal
    with pytest.raises(QuotaExceeded):
        q.admit(None, 1)
    assert q.clients() == ["a", "anonymous", "b"]


def test_quota_refund_restores_tokens_clamped_at_burst():
    clock = FakeClock()
    q = ClientQuotas(QuotaPolicy(rate=1.0, burst=4.0), clock=clock)
    q.admit("a", 3)
    q.refund("a", 3)                      # routing rejected it: full undo
    assert q.tokens("a") == 4.0
    q.admit("a", 1)
    q.refund("a", 100)                    # refund clamps at burst
    assert q.tokens("a") == 4.0
    ClientQuotas(None).refund("a", 1)     # disabled quotas: no-op


def test_quota_disabled_admits_everything():
    q = ClientQuotas(None)
    for _ in range(100):
        q.admit("anyone", 1e9)
    assert not q.enabled and q.tokens("anyone") is None


# ---------------------------------------------------------------------------
# wire protocol
# ---------------------------------------------------------------------------

class ChunkSock:
    """recv() serving a byte string in fixed-size chunks."""

    def __init__(self, data, chunk=3):
        self.data = data
        self.chunk = chunk

    def recv(self, n):
        out, self.data = self.data[:self.chunk], self.data[self.chunk:]
        return out


def test_line_reader_reassembles_chunks():
    r = protocol.LineReader(ChunkSock(b'{"a":1}\n{"b":2}\n'))
    assert json.loads(r.next_line()) == {"a": 1}
    assert json.loads(r.next_line()) == {"b": 2}
    assert r.next_line() is None          # clean EOF


def test_line_reader_mid_line_eof_and_bound():
    with pytest.raises(protocol.ProtocolError):
        protocol.LineReader(ChunkSock(b'{"a":')).next_line()
    with pytest.raises(protocol.ProtocolError):
        protocol.LineReader(ChunkSock(b"x" * 100, chunk=50),
                            max_line_bytes=10).next_line()


def test_error_code_taxonomy():
    assert protocol.error_code_for(QuotaExceeded("x")) == "quota_exceeded"
    assert protocol.error_code_for(TierOverloaded("x")) == "overloaded"
    assert protocol.error_code_for(EngineOverloaded("x")) == "overloaded"
    assert protocol.error_code_for(RequestTimeout("x")) == "timeout"
    assert protocol.error_code_for(ReplicaUnavailable("x")) == "unavailable"
    assert protocol.error_code_for(ValueError("x")) == "bad_request"
    assert protocol.error_code_for(RuntimeError("x")) == "internal"
    # unknown codes degrade to internal rather than inventing taxonomy
    assert protocol.error_response(1, "no_such_code", "m")["error"] == \
        "internal"


def test_decode_line_rejects_non_objects():
    with pytest.raises(protocol.ProtocolError):
        protocol.decode_line(b"[1, 2]")
    with pytest.raises(protocol.ProtocolError):
        protocol.decode_line(b"{nope")


# ---------------------------------------------------------------------------
# the TCP front end (real sockets, fake engines)
# ---------------------------------------------------------------------------

@pytest.fixture
def fake_tier():
    engines = [FakeEngine("auto"), FakeEngine("auto")]
    tier = ServingTier(engines, quota=None, monitor_interval_s=0.05)
    tier.start()
    yield tier, engines
    tier.stop(timeout_s=10)


def test_tier_end_to_end_and_out_of_order_ids(fake_tier):
    tier, _ = fake_tier
    with TierClient("127.0.0.1", tier.port) as c:
        # pipelined: several requests in flight, demuxed on echoed id
        ids = [c.submit("score", [[float(i), 0, 0, 0]]) for i in range(5)]
        got = c.drain(ids)
        assert all(got[rid]["ok"] for rid in ids)
        # seeds mint in tier admission order: i-th request sees seed i
        assert [got[rid]["result"][0] for rid in ids] == \
            [i * 1000.0 + float(i) for i in range(5)]
        info = c.info()
        assert info["replicas"] == 2 and info["ops"] == \
            ["decode", "encode", "score"]
        stats = c.stats()
        assert stats["router"]["router/routed"] == 5
        assert len(stats["replicas"]) == 2


def test_tier_typed_errors_keep_connection_alive(fake_tier):
    tier, engines = fake_tier
    with TierClient("127.0.0.1", tier.port) as c:
        # malformed JSON -> bad_request, connection survives
        c._sock.sendall(b"this is not json\n")
        resp = c._read_one()
        assert resp["ok"] is False and resp["error"] == "bad_request"
        # empty payload -> bad_request
        with pytest.raises(TierError) as ei:
            c.request("score", [])
        assert ei.value.code == "bad_request"
        # multi-row + seed -> bad_request (seed names ONE row's stream)
        rid = c.submit("score", [[0, 0, 0, 0], [1, 1, 1, 1]], seed=3)
        assert c.drain([rid])[rid]["error"] == "bad_request"
        # out-of-int32-range seed dies at the wire as THIS client's
        # bad_request — inside a replica it would error a whole coalesced
        # batch and cascade as a replica failure across the fleet
        for bad_seed in (-1, 2 ** 31):
            rid = c.submit("score", [[0, 0, 0, 0]], seed=bad_seed)
            assert c.drain([rid])[rid]["error"] == "bad_request"
        assert all(rep["healthy"] for rep in tier.stats()["replicas"])
        # every replica shedding -> overloaded, typed
        for e in engines:
            e.mode = "shed"
        with pytest.raises(TierError) as ei:
            c.score([[0, 0, 0, 0]])
        assert ei.value.code == "overloaded"
        for e in engines:
            e.mode = "auto"
        # and the SAME connection still serves
        assert c.score([[1, 0, 0, 0]])


def test_tier_quota_rejection_is_typed_response():
    engines = [FakeEngine("auto")]
    tier = ServingTier(engines, quota=QuotaPolicy(rate=0.001, burst=2))
    tier.start()
    try:
        with TierClient("127.0.0.1", tier.port, client_id="t1") as c:
            assert c.score([[0, 0, 0, 0], [0, 0, 0, 0]])   # burst covers 2
            with pytest.raises(TierError) as ei:
                c.score([[0, 0, 0, 0]])                    # dry
            assert ei.value.code == "quota_exceeded"
            # another client's bucket is untouched
            with TierClient("127.0.0.1", tier.port, client_id="t2") as c2:
                assert c2.score([[0, 0, 0, 0]])
        assert tier.registry.counter(
            "router/quota_rejections").value == 1
    finally:
        tier.stop(timeout_s=10)


def test_quota_refunded_when_routing_rejects():
    """The quota meters SERVED work: a request admitted past the token
    bucket but rejected by the fleet (every replica shedding) gets its
    tokens back — sustained overload must surface as 'overloaded', never
    stack 'quota_exceeded' on top of it."""
    eng = FakeEngine("shed")
    tier = ServingTier([eng], quota=QuotaPolicy(rate=0.001, burst=2))
    tier.start()
    try:
        with TierClient("127.0.0.1", tier.port, client_id="t1") as c:
            for _ in range(4):       # 4 rejects > burst 2: only refunds
                with pytest.raises(TierError) as ei:
                    c.score([[0, 0, 0, 0]])
                assert ei.value.code == "overloaded"
            eng.mode = "auto"        # capacity restored: tokens were kept
            assert c.score([[1, 0, 0, 0]])
        # burst 2 - 1 served (real clock: the 1e-3/s refill drifts a hair)
        assert tier.quotas.tokens("t1") == pytest.approx(1.0, abs=0.01)
    finally:
        tier.stop(timeout_s=10)


def test_tier_mid_burst_replica_kill_loses_nothing():
    """The acceptance pin: a replica killed mid-burst loses zero responses —
    every accepted request gets a result (rerouted) or a typed error."""
    bad, good = FakeEngine("manual"), FakeEngine("manual")
    tier = ServingTier([bad, good], monitor_interval_s=0.05)
    tier.start()
    try:
        with TierClient("127.0.0.1", tier.port) as c:
            ids = [c.submit("score", [[float(i), 0, 0, 0]], k=(i % 2) + 1)
                   for i in range(12)]
            # wait for the burst to spread over both replicas, then kill one
            wait_until(lambda: bad.submitted + good.submitted == 12,
                       msg="burst fully routed")
            assert bad.submitted and good.submitted
            bad.finish(exc=RuntimeError("replica killed mid-burst"))
            # complete everything the healthy replica now holds (original
            # work + rerouted work); keep finishing until the wire drains
            done = {}
            t = threading.Thread(
                target=lambda: done.update(c.drain(ids)), daemon=True)
            t.start()
            deadline = time.monotonic() + 10
            while t.is_alive() and time.monotonic() < deadline:
                good.finish()
                time.sleep(0.01)
            t.join(timeout=1)
            assert not t.is_alive(), "burst responses never drained"
            assert len(done) == 12
            for i, rid in enumerate(ids):
                assert done[rid]["ok"], done[rid]
                # rerouted rows carry their ORIGINAL seed: result is the
                # same value the dead replica would have returned
                assert done[rid]["result"][0] == i * 1000.0 + float(i)
        st = tier.stats()
        assert st["router"]["router/reroutes"] >= 1
        assert st["router"]["router/replica_failures"] == 1
        healthy = [r["healthy"] for r in st["replicas"]]
        assert healthy.count(False) == 1
    finally:
        tier.stop(timeout_s=10)


def test_tier_stop_answers_pending_requests():
    eng = FakeEngine("manual")
    tier = ServingTier([eng], monitor_interval_s=0.05)
    tier.start()
    c = TierClient("127.0.0.1", tier.port)
    try:
        ids = [c.submit("score", [[1, 0, 0, 0]]) for _ in range(4)]
        wait_until(lambda: eng.submitted == 4, msg="requests routed")
        # graceful drain: engine.stop() (the fake completes its held work),
        # responses flushed BEFORE sockets close
        stopper = threading.Thread(target=tier.stop, daemon=True)
        stopper.start()
        got = c.drain(ids)
        stopper.join(timeout=10)
        assert not stopper.is_alive()
        assert len(got) == 4 and all(got[rid]["ok"] for rid in ids)
    finally:
        c.close()
        tier.stop(timeout_s=5)


def test_prometheus_router_schema(fake_tier):
    """Router metrics are visible on the exporter page with stable names."""
    from iwae_replication_project_tpu.telemetry import prometheus_text

    tier, _ = fake_tier
    with TierClient("127.0.0.1", tier.port) as c:
        c.score([[1, 0, 0, 0]])
    page = prometheus_text(tier.registry)
    for counter in ("routed", "completed", "errors", "reroutes", "sheds",
                    "quota_rejections", "replica_failures", "affinity_hits",
                    "stall_drains", "probe_readmits"):
        assert f"iwae_router_{counter}_total" in page, counter
    for gauge in ("iwae_router_outstanding", "iwae_router_replicas",
                  "iwae_router_inflight_r0", "iwae_router_inflight_r1",
                  "iwae_router_healthy_r0", "iwae_router_healthy_r1"):
        assert f"# TYPE {gauge} gauge" in page, gauge
    assert "iwae_router_routed_total 1" in page


# ---------------------------------------------------------------------------
# RemoteEngine: fleet composition over processes
# ---------------------------------------------------------------------------

def test_remote_engine_engine_surface(fake_tier):
    tier, _ = fake_tier
    with RemoteEngine("127.0.0.1", tier.port) as rem:
        assert rem.row_dims == {"score": 4, "encode": 4, "decode": 4}
        assert rem.k == 5
        # explicit seed rides through to the leaf engine bitwise
        assert rem.submit("score", [2.0, 0, 0, 0],
                          seed=9).result(timeout=5) == 9002.0
        with pytest.raises(ValueError):
            rem.submit("nope", [0, 0, 0, 0])
        with pytest.raises(ValueError):
            rem.submit("score", [0, 0])      # wrong feature count
        with pytest.raises(ValueError):
            rem.submit("score", [0, 0, 0, 0], seed=2 ** 31)  # int32 bound


def test_remote_engine_connection_loss_fails_outstanding():
    eng = FakeEngine("manual")
    tier = ServingTier([eng], monitor_interval_s=0.05)
    tier.start()
    rem = RemoteEngine("127.0.0.1", tier.port)
    f = rem.submit("score", [0, 0, 0, 0], seed=1)
    wait_until(lambda: eng.submitted == 1, msg="request routed")
    # the tier dies under the proxy: the graceful drain answers the held
    # request first, then the closed connection poisons the proxy — the
    # future must RESOLVE either way (result, or the typed unavailable)
    tier.stop(timeout_s=5)
    wait_until(f.done, msg="future resolution on connection loss")
    if f.exception() is None:
        assert f.result() == 1000.0
    else:
        assert isinstance(f.exception(), ReplicaUnavailable)
    wait_until(lambda: rem._dead is not None, msg="proxy poisoning")
    with pytest.raises(ReplicaUnavailable):
        rem.submit("score", [0, 0, 0, 0])
    rem.close()


def test_parent_router_over_remote_tiers():
    """Fleet-of-fleets: a parent router over two RemoteEngine proxies, each
    fronting its own child tier; a child tier killed mid-flight has its work
    rerouted to the surviving child with the parent's original seeds."""
    child_a = ServingTier([FakeEngine("auto")], monitor_interval_s=0.05)
    child_b = ServingTier([FakeEngine("auto")], monitor_interval_s=0.05)
    child_a.start(), child_b.start()
    try:
        rem_a = RemoteEngine("127.0.0.1", child_a.port)
        rem_b = RemoteEngine("127.0.0.1", child_b.port)
        parent = ReplicaRouter([rem_a, rem_b], affinity_slack=0)
        got = [parent.submit("score", [1.0, 0, 0, 0], k=(i % 2) + 1)
               .result(timeout=5) for i in range(6)]
        # parent-minted seeds (admission order) determine results, NOT which
        # child served: bitwise independent of process placement
        assert got == [i * 1000.0 + 1.0 for i in range(6)]
        parent.drain(timeout_s=5)
    finally:
        child_a.stop(timeout_s=5), child_b.stop(timeout_s=5)


# ---------------------------------------------------------------------------
# elastic fleet shape: capability refresh + the slo control op
# ---------------------------------------------------------------------------

def _engine_with(mode="auto", model=None, k_max=None, sharded=False):
    e = FakeEngine(mode)
    if model is not None:
        e.model = model
        e.models = (model,)
        # labeled engines take the model kwarg the router forwards
        base = e.submit
        e.submit = lambda op, row, k=None, *, seed=None, model=None: \
            base(op, row, k, seed=seed)
    if k_max is not None:
        e.k_max = k_max
    if sharded:
        e.sharded = True
    return e


def test_fleet_grow_then_shrink_capability_refresh():
    """The capability-snapshot pin: k_max / models / large-k classification
    recompute on every fleet-shape change, and the default model is sticky
    (a grown-then-shrunk fleet never silently reroutes model-less traffic
    onto different weights)."""
    fast = _engine_with(model="mnist", k_max=64)
    r = ReplicaRouter([fast])
    assert (r.k_max, r.large_k_threshold) == (64, None)
    assert r.models == frozenset({"mnist"}) and r.default_model == "mnist"

    big = r.add_replica(_engine_with(k_max=4096, sharded=True))
    # a sharded replica joined: the large-k class exists now, and the
    # fleet-wide k ceiling grew
    assert (r.k_max, r.large_k_threshold) == (4096, 64)

    omni = r.add_replica(_engine_with(model="omniglot", k_max=32))
    assert r.models == frozenset({"mnist", "omniglot"})
    assert r.large_k_threshold == 32     # min fast k_max splits the classes
    assert r.default_model == "mnist"    # sticky through the growth

    # traffic rides the grown fleet with admission-order seeds
    got = [r.submit("score", [1.0, 0, 0, 0], k=(i % 3) + 1,
                    model="mnist").result(timeout=5) for i in range(6)]
    assert got == [i * 1000.0 + 1.0 for i in range(6)]

    # shrink back: every capability bound recomputes downward too
    r.remove_replica(omni)
    assert r.models == frozenset({"mnist"}) and r.large_k_threshold == 64
    r.remove_replica(big)
    assert (r.k_max, r.large_k_threshold) == (64, None)
    assert r.default_model == "mnist"
    with pytest.raises(ValueError):
        r.remove_replica(big)            # stable indices never recycle
    with pytest.raises(ValueError):
        r.remove_replica(0)              # the last replica never drains
    r.drain(timeout_s=5)


def test_slo_control_op_and_remote_forwarding(fake_tier):
    """Satellite pin: the ``slo`` wire op returns the SLOMonitor snapshot
    beside stats/traces, and RemoteEngine forwards it — a parent
    autoscaler reads a child tier's burn rates as JSON."""
    from iwae_replication_project_tpu.serving.fleet import wire_signals

    tier, _ = fake_tier
    with TierClient("127.0.0.1", tier.port) as c:
        c.score([[1.0, 0, 0, 0]])
        doc = c.slo()
        assert doc["enabled"] is True and "score" in doc["slo"]
        assert doc["slo"]["score"]["windows"]["5m"]["requests"] == 1
    with RemoteEngine("127.0.0.1", tier.port) as rem:
        rdoc = rem.slo()
        assert rdoc["enabled"] is True and "score" in rdoc["slo"]
        # the wire doc reduces into the controller's snapshot schema
        snap = wire_signals(rdoc, replica_states=[
            {"index": 0, "healthy": True, "draining": False, "inflight": 0}])
        assert snap.requests_in("5m") >= 1 and snap.replicas == 1


def test_slo_control_op_disabled_tier():
    tier = ServingTier([FakeEngine("auto")], slo=False,
                       monitor_interval_s=0.05)
    tier.start()
    try:
        with TierClient("127.0.0.1", tier.port) as c:
            assert c.slo() == {"enabled": False, "slo": {}}
    finally:
        tier.stop(timeout_s=10)


# ---------------------------------------------------------------------------
# real-engine integration: fleet parity + zero recompiles (the AOT pin)
# ---------------------------------------------------------------------------

D = 32
TINY = dict(n_hidden_enc=(16, 8), n_latent_enc=(8, 4),
            n_hidden_dec=(8, 16), n_latent_dec=(8, D))


@pytest.fixture(scope="module")
def tiny_fleet():
    import jax

    from iwae_replication_project_tpu.models import iwae as model
    from iwae_replication_project_tpu.serving import ServingEngine

    cfg = model.ModelConfig(x_dim=D, **TINY)
    params = model.init_params(jax.random.PRNGKey(0), cfg)

    def engine():
        return ServingEngine(params=params, model_config=cfg, k=4,
                             max_batch=8, timeout_s=30.0)

    x = (np.random.RandomState(1).rand(40, D) > 0.5).astype(np.float32)
    return {"engine": engine, "x": x}


def test_fleet_bitwise_parity_with_direct_engine(tiny_fleet):
    """The tentpole semantic pin: a 2-replica tier over TCP returns results
    bitwise identical to ONE direct in-process engine fed the same rows in
    the same order — routing, padding, and the wire are all invisible."""
    x = tiny_fleet["x"][:17]
    direct = tiny_fleet["engine"]()
    ref = direct.score(x)          # seeds 0..16 in submit order
    direct.stop()

    tier = ServingTier([tiny_fleet["engine"](), tiny_fleet["engine"]()],
                       monitor_interval_s=0.05)
    tier.warmup(ops=("score",))
    tier.start()
    try:
        with TierClient("127.0.0.1", tier.port) as c:
            # ragged multi-row requests; tier admission order = row order
            got, i = [], 0
            for n in (1, 3, 7, 2, 4):
                got.extend(c.score(x[i:i + n].tolist()))
                i += n
        wire = np.asarray(got, dtype=ref.dtype)
        assert np.array_equal(wire, ref), \
            "fleet results differ from the direct single-engine run"
    finally:
        tier.stop(timeout_s=10)


def test_multi_client_ragged_stream_zero_recompiles(tiny_fleet):
    """The satellite bugfix pin: client identity (client id, quota state)
    must never reach an AOT program signature — a warmed tier serving a
    ragged MULTI-client stream compiles nothing and adds no registry
    entries, and the traced-program goldens (tests/test_audit.py) stay
    unchanged because the serving programs never see a client field."""
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, registry_signatures, stats_delta)

    tier = ServingTier([tiny_fleet["engine"](), tiny_fleet["engine"]()],
                       quota=QuotaPolicy(rate=1e6, burst=1e6),
                       monitor_interval_s=0.05)
    tier.warmup(ops=("score", "encode"))
    tier.start()
    try:
        sigs0 = set(map(str, registry_signatures()))
        s0 = cache_stats()
        x = tiny_fleet["x"]
        clients = ("tenant-a", "tenant-b", None, "tenant-c")
        conns = [TierClient("127.0.0.1", tier.port, client_id=cid)
                 for cid in clients]
        try:
            ids = []
            for j, c in enumerate(conns):
                n = (1, 3, 7, 5)[j % 4]
                ids.append((c, c.submit("score", x[:n].tolist())))
                ids.append((c, c.submit("encode", x[:n + 1].tolist())))
            for c, rid in ids:
                resp = c.drain([rid])[rid]
                assert resp["ok"], resp
        finally:
            for c in conns:
                c.close()
        d = stats_delta(s0)
        assert d["aot_misses"] == 0, \
            f"multi-client stream caused AOT compiles: {d}"
        assert d["persistent_cache_misses"] == 0, d
        assert set(map(str, registry_signatures())) == sigs0, \
            "client identity leaked into AOT program signatures"
    finally:
        tier.stop(timeout_s=10)
