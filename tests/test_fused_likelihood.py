"""Pallas fused-likelihood kernel parity tests (interpret mode on CPU):
forward and VJP must match the unfused XLA composition exactly."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import ModelConfig, init_params, log_weights
from iwae_replication_project_tpu.ops.fused_likelihood import (
    _reference_impl,
    fused_bernoulli_ll,
)


@pytest.fixture
def problem():
    rs = np.random.RandomState(0)
    k, b, h, d = 5, 6, 16, 12
    h1 = jnp.asarray(rs.randn(k, b, h).astype(np.float32))
    w = jnp.asarray(rs.randn(h, d).astype(np.float32) * 0.2)
    bias = jnp.asarray(rs.randn(d).astype(np.float32) * 0.1)
    x = jnp.asarray((rs.rand(b, d) > 0.5).astype(np.float32))
    return h1, w, bias, x


class TestKernelParity:
    def test_forward_matches_reference(self, problem):
        h1, w, bias, x = problem
        got = fused_bernoulli_ll(h1, w, bias, x, True)
        want = _reference_impl(h1, w, bias, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-5)

    def test_large_pixel_dim(self):
        """x_dim beyond one 128-lane pad block (regression: pixels past the
        first pad block were silently dropped)."""
        rs = np.random.RandomState(2)
        k, b, h, d = 3, 4, 8, 1024
        h1 = jnp.asarray(rs.randn(k, b, h).astype(np.float32))
        w = jnp.asarray(rs.randn(h, d).astype(np.float32) * 0.1)
        bias = jnp.asarray(rs.randn(d).astype(np.float32) * 0.1)
        x = jnp.asarray((rs.rand(b, d) > 0.5).astype(np.float32))
        got = fused_bernoulli_ll(h1, w, bias, x, True)
        want = _reference_impl(h1, w, bias, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-4)
        g = jax.grad(lambda ww: jnp.sum(fused_bernoulli_ll(h1, ww, bias, x, True)))(w)
        gr = jax.grad(lambda ww: jnp.sum(_reference_impl(h1, ww, bias, x)))(w)
        np.testing.assert_allclose(np.asarray(g), np.asarray(gr), rtol=1e-4,
                                   atol=1e-4)

    def test_forward_various_k(self):
        # exercises the K-padding path: k below, equal to, and above TILE_K,
        # including non-multiples
        rs = np.random.RandomState(1)
        b, h, d = 6, 16, 12
        w = jnp.asarray(rs.randn(h, d).astype(np.float32) * 0.2)
        bias = jnp.asarray(rs.randn(d).astype(np.float32) * 0.1)
        x = jnp.asarray((rs.rand(b, d) > 0.5).astype(np.float32))
        for k in (1, 3, 8, 10, 17):
            h1 = jnp.asarray(rs.randn(k, b, h).astype(np.float32))
            got = fused_bernoulli_ll(h1, w, bias, x, True)
            want = _reference_impl(h1, w, bias, x)
            np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                       rtol=1e-5, atol=1e-5, err_msg=f"k={k}")

    @pytest.mark.parametrize("k", (1, 3, 7, 17))
    @pytest.mark.parametrize("b", (1, 3, 7, 17))
    def test_masked_tiles_odd_shapes_fwd_bwd(self, k, b):
        """Satellite (ISSUE 6): the _pixel_pad/_pad_axis zero padding must be
        invisible at every odd k/batch size and a non-multiple-of-128 pixel
        dim — forward AND backward against _reference_impl, plus the output
        fed through a logsumexp reduction (a padded row leaking into the
        ``exp`` sum would shift the bound even when the slice looks right).
        """
        rs = np.random.RandomState(k * 100 + b)
        h, d = 16, 130  # 130 pixels: one full 128-lane block + a ragged tail
        h1 = jnp.asarray(rs.randn(k, b, h).astype(np.float32))
        w = jnp.asarray(rs.randn(h, d).astype(np.float32) * 0.2)
        bias = jnp.asarray(rs.randn(d).astype(np.float32) * 0.1)
        x = jnp.asarray((rs.rand(b, d) > 0.5).astype(np.float32))
        got = fused_bernoulli_ll(h1, w, bias, x, True)
        want = _reference_impl(h1, w, bias, x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-4)

        from iwae_replication_project_tpu.ops.logsumexp import logmeanexp

        def bound_f(ww):
            return jnp.mean(logmeanexp(fused_bernoulli_ll(h1, ww, bias, x,
                                                          True), axis=0))

        def bound_r(ww):
            return jnp.mean(logmeanexp(_reference_impl(h1, ww, bias, x),
                                       axis=0))

        np.testing.assert_allclose(float(bound_f(w)), float(bound_r(w)),
                                   rtol=1e-6)
        g_f = jax.grad(bound_f)(w)
        g_r = jax.grad(bound_r)(w)
        np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_r),
                                   rtol=1e-4, atol=1e-5)

    def test_gradients_match_reference(self, problem):
        h1, w, bias, x = problem

        def fused_loss(h1, w, bias):
            return jnp.sum(fused_bernoulli_ll(h1, w, bias, x, True) ** 2)

        def ref_loss(h1, w, bias):
            return jnp.sum(_reference_impl(h1, w, bias, x) ** 2)

        g_f = jax.grad(fused_loss, argnums=(0, 1, 2))(h1, w, bias)
        g_r = jax.grad(ref_loss, argnums=(0, 1, 2))(h1, w, bias)
        for a, b_, name in zip(g_f, g_r, ("dh1", "dw", "dbias")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-5, err_msg=name)

    def test_jit_and_vmap_compose(self, problem):
        h1, w, bias, x = problem
        f = jax.jit(lambda *a: fused_bernoulli_ll(*a, True))
        np.testing.assert_allclose(np.asarray(f(h1, w, bias, x)),
                                   np.asarray(_reference_impl(h1, w, bias, x)),
                                   rtol=1e-5, atol=1e-5)

    def test_fits_vmem_thresholds(self):
        """The VMEM gate matches the measured v5e limits (flagship H=200,
        784 pixels): forward fits up to batch ~300, not 400; the larger
        backward working set stops fitting around batch 150-200; the
        flagship train shape (B=100) fits both ways."""
        from iwae_replication_project_tpu.ops.fused_likelihood import fits_vmem
        assert fits_vmem(8, 100, 200, 784)
        assert fits_vmem(8, 100, 200, 784, grad=True)
        assert fits_vmem(8, 300, 200, 784)
        assert not fits_vmem(8, 400, 200, 784)
        assert not fits_vmem(8, 200, 200, 784, grad=True)

    def test_vmem_budget_env_override(self, monkeypatch):
        """IWAE_FUSED_VMEM_BUDGET (bytes) overrides the per-generation budget
        — the test/ops lever for forcing the unfused fallback."""
        from iwae_replication_project_tpu.ops import fused_likelihood as fl
        monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", "1")
        assert not fl.fits_vmem(8, 4, 16, 12)
        assert not fl.kernel_usable(8, 4, 16, 12, interpret=True)
        monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", str(1 << 30))
        assert fl.fits_vmem(8, 400, 200, 784)

    def test_bf16_itemsize_scales_operand_terms_only(self):
        """itemsize scales the streamed operand blocks but NOT the f32
        logits tile / accumulators (the kernel computes with
        preferred_element_type=f32): batch 400's ~11.5M f32 logits tile
        alone keeps it over budget even with bf16 operands, while batch 350
        (f32 est ~14.3M) is admitted at bf16 (~12.2M)."""
        from iwae_replication_project_tpu.ops.fused_likelihood import fits_vmem
        assert not fits_vmem(8, 400, 200, 784, itemsize=2)
        assert not fits_vmem(8, 350, 200, 784, itemsize=4)
        assert fits_vmem(8, 350, 200, 784, itemsize=2)

    def test_probe_compile_failure_falls_back(self, monkeypatch):
        """A shape that passes the estimate but fails to compile (other chip
        generation, Mosaic limit...) must warn once and permanently use the
        unfused path — never crash the enclosing jit (VERDICT r4 Weak #3)."""
        from iwae_replication_project_tpu.ops import fused_likelihood as fl

        calls = []

        def boom(*a, **kw):
            calls.append(a)
            raise RuntimeError("scoped vmem exceeded (simulated)")

        monkeypatch.setattr(fl, "_probe_cache", {})
        monkeypatch.setattr(fl, "_bwd_pallas", boom)
        monkeypatch.setattr(fl, "_fwd_pallas", boom)
        with pytest.warns(RuntimeWarning, match="failed to compile"):
            assert not fl.kernel_usable(8, 4, 16, 12, interpret=False)
        assert len(calls) == 1
        # cached: the second query neither warns nor re-probes
        assert not fl.kernel_usable(8, 4, 16, 12, interpret=False)
        assert len(calls) == 1

    def test_probe_cache_invalidated_by_budget_change(self, monkeypatch):
        """A mid-process IWAE_FUSED_VMEM_BUDGET change must re-probe, not
        keep the verdict cached under the old budget: the effective budget is
        part of the probe-cache key (ADVICE r5)."""
        from iwae_replication_project_tpu.ops import fused_likelihood as fl

        calls = []

        def fake_probe(*a, **kw):
            calls.append(a)
            return True

        monkeypatch.setattr(fl, "_probe_cache", {})
        monkeypatch.setattr(fl, "_probe_compiles", fake_probe)
        monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", str(1 << 30))
        assert fl.kernel_usable(8, 4, 16, 12, interpret=False)
        assert len(calls) == 1
        # same budget -> cached verdict, no second probe
        assert fl.kernel_usable(8, 4, 16, 12, interpret=False)
        assert len(calls) == 1
        # changed budget -> distinct key -> fresh probe
        monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", str((1 << 30) + 1))
        assert fl.kernel_usable(8, 4, 16, 12, interpret=False)
        assert len(calls) == 2

    def test_oversized_backward_falls_back_exactly(self):
        """A batch over the backward VMEM budget still differentiates: the
        custom VJP swaps in the XLA backward, whose grads must match the
        unfused reference."""
        rs = np.random.RandomState(1)
        k, b, h, d = 8, 200, 200, 784  # grad=True estimate over budget
        h1 = jnp.asarray(rs.randn(k, b, h).astype(np.float32) * 0.1)
        w = jnp.asarray(rs.randn(h, d).astype(np.float32) * 0.05)
        bias = jnp.zeros((d,), jnp.float32)
        x = jnp.asarray((rs.rand(b, d) > 0.5).astype(np.float32))
        g_f = jax.grad(lambda a, ww, bb: jnp.sum(
            fused_bernoulli_ll(a, ww, bb, x, True)), argnums=(0, 1, 2))(
            h1, w, bias)
        g_r = jax.grad(lambda a, ww, bb: jnp.sum(
            _reference_impl(a, ww, bb, x)), argnums=(0, 1, 2))(h1, w, bias)
        for a, b_, name in zip(g_f, g_r, ("dh1", "dw", "dbias")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                       rtol=1e-4, atol=1e-4, err_msg=name)

    def test_oversized_forward_dispatch_falls_back(self):
        """log_px_given_h with fused_likelihood=True must compute (not crash)
        at batches whose forward exceeds the kernel's VMEM budget, agreeing
        with the unfused config."""
        rs = np.random.RandomState(2)
        cfg_f = ModelConfig(n_hidden_enc=(200,), n_latent_enc=(100,),
                            n_hidden_dec=(200,), n_latent_dec=(784,),
                            likelihood="logits", fused_likelihood=True)
        cfg_u = ModelConfig(n_hidden_enc=(200,), n_latent_enc=(100,),
                            n_hidden_dec=(200,), n_latent_dec=(784,),
                            likelihood="logits", fused_likelihood=False)
        from iwae_replication_project_tpu.models.iwae import log_px_given_h
        params = init_params(jax.random.PRNGKey(0), cfg_f)
        h1 = jnp.asarray(rs.randn(8, 500, 100).astype(np.float32) * 0.1)
        x = jnp.asarray((rs.rand(500, 784) > 0.5).astype(np.float32))
        got = log_px_given_h(params, cfg_f, x, h1)
        want = log_px_given_h(params, cfg_u, x, h1)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-4)



class TestModelIntegration:
    def test_fused_model_matches_unfused(self, rng):
        cfg_fused = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                                n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                                likelihood="logits", fused_likelihood=True)
        cfg_plain = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                                n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                                likelihood="logits")
        params = init_params(rng, cfg_plain)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (6, 12)) > 0.5).astype(jnp.float32)
        key = jax.random.PRNGKey(2)
        a = log_weights(params, cfg_fused, key, x, k=4)
        b = log_weights(params, cfg_plain, key, x, k=4)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-5)

    def test_fused_requires_logits_mode(self):
        with pytest.raises(ValueError):
            ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                        n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                        fused_likelihood=True)

    @pytest.mark.slow
    def test_fused_training_grads_finite(self, rng):
        from iwae_replication_project_tpu.objectives import (
            ObjectiveSpec, objective_value_and_grad)
        cfg = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                          n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                          likelihood="logits", fused_likelihood=True)
        params = init_params(rng, cfg)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (6, 12)) > 0.5).astype(jnp.float32)
        val, grads = objective_value_and_grad(ObjectiveSpec("IWAE", k=4), params,
                                              cfg, jax.random.PRNGKey(2), x)
        assert np.isfinite(float(val))
        assert all(np.all(np.isfinite(np.asarray(l))) for l in jax.tree.leaves(grads))
