"""Blocked hot-loop tests (ops/hot_loop.py): (k, batch)-tiled kernel parity
in interpret mode, the blocked-scan fallback, trace-time path selection, and
the kernel_path telemetry — ISSUE 6."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import (
    ModelConfig,
    init_params,
    log_weights,
)
from iwae_replication_project_tpu.ops import hot_loop as hl
from iwae_replication_project_tpu.ops.logsumexp import logmeanexp


def _mk(k, b, h1d, hid, d, seed=0):
    rs = np.random.RandomState(seed)
    return (jnp.asarray(rs.randn(k, b, h1d).astype(np.float32)),
            jnp.asarray(rs.randn(h1d, hid).astype(np.float32) * 0.2),
            jnp.asarray(rs.randn(hid).astype(np.float32) * 0.1),
            jnp.asarray(rs.randn(hid, hid).astype(np.float32) * 0.2),
            jnp.asarray(rs.randn(hid).astype(np.float32) * 0.1),
            jnp.asarray(rs.randn(hid, d).astype(np.float32) * 0.2),
            jnp.asarray(rs.randn(d).astype(np.float32) * 0.1),
            jnp.asarray((rs.rand(b, d) > 0.5).astype(np.float32)))


def _ref_grads(args, g):
    def f(*ps):
        return hl._reference_impl(*ps, args[-1])

    _, vjp = jax.vjp(f, *args[:-1])
    return vjp(g)


#: the satellite shape grid: odd k/batch (1, 3, 7, 17) x non-multiple-of-128
#: pixel dims, plus batch sizes that force PARTIAL batch tiles (tb=128)
SHAPES = [(1, 1, 12), (3, 7, 130), (7, 17, 140), (17, 3, 12), (10, 300, 12)]


class TestBlockedKernelParity:
    @pytest.mark.parametrize("k,b,d", SHAPES)
    def test_forward_and_backward_match_reference(self, k, b, d):
        args = _mk(k, b, 8, 16, d)
        tk, tb = min(8, k), (128 if b > 128 else b)
        want = hl._reference_impl(*args)
        got = hl._fwd_pallas(*args, tk=tk, tb=tb, interpret=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-5, atol=1e-4)
        g = jnp.asarray(np.random.RandomState(1).randn(k, b).astype(np.float32))
        got_g = hl._bwd_pallas(*args, g, tk=tk, tb=tb, interpret=True)
        want_g = _ref_grads(args, g)
        for a, w, name in zip(got_g, want_g,
                              ("dh", "dw1", "db1", "dw2", "db2", "dw3", "db3")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-4, atol=1e-4, err_msg=name)

    def test_custom_vjp_entry_grads(self):
        """Grads through the public custom-VJP entry (pallas fwd + pallas
        bwd in interpret mode) against autodiff of the reference."""
        k, b, d = 5, 6, 12
        args = _mk(k, b, 8, 16, d)
        x = args[-1]

        def loss_f(*ps):
            return jnp.sum(hl._fused_block_ll(*ps, x, min(8, k), b, True,
                                              None) ** 2)

        def loss_r(*ps):
            return jnp.sum(hl._reference_impl(*ps, x) ** 2)

        g_f = jax.grad(loss_f, argnums=tuple(range(7)))(*args[:-1])
        g_r = jax.grad(loss_r, argnums=tuple(range(7)))(*args[:-1])
        for a, w in zip(g_f, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-4, atol=1e-4)

    def test_bwd_tile_fallback_to_xla(self, monkeypatch):
        """When no backward tile fits the budget the custom VJP swaps in the
        XLA backward while keeping the fused forward — grads must still
        match the reference."""
        k, b, d = 5, 6, 12
        args = _mk(k, b, 8, 16, d)
        x = args[-1]
        real = hl.kernel_usable_block
        monkeypatch.setattr(
            hl, "kernel_usable_block",
            lambda *a, **kw: None if kw.get("grad") else real(*a, **kw))

        def loss_f(*ps):
            return jnp.sum(hl._fused_block_ll(*ps, x, min(8, k), b, True,
                                              None) ** 2)

        def loss_r(*ps):
            return jnp.sum(hl._reference_impl(*ps, x) ** 2)

        g_f = jax.grad(loss_f, argnums=tuple(range(7)))(*args[:-1])
        g_r = jax.grad(loss_r, argnums=tuple(range(7)))(*args[:-1])
        for a, w in zip(g_f, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("k,b,d", SHAPES)
    def test_padding_never_leaks_into_logsumexp(self, k, b, d):
        """Satellite: the zero-padded (k, batch, pixel) tiles must be
        invisible to downstream ops.logsumexp reductions — logmeanexp over
        the fused output equals logmeanexp over the reference for every
        odd shape, fwd AND bwd."""
        args = _mk(k, b, 8, 16, d)
        tk, tb = min(8, k), (128 if b > 128 else b)

        def bound_f(*ps):
            ll = hl._fused_block_ll(*ps, args[-1], tk, tb, True, None)
            return jnp.mean(logmeanexp(ll, axis=0))

        def bound_r(*ps):
            return jnp.mean(logmeanexp(hl._reference_impl(*ps, args[-1]),
                                       axis=0))

        got, want = bound_f(*args[:-1]), bound_r(*args[:-1])
        np.testing.assert_allclose(float(got), float(want), rtol=1e-6)
        g_f = jax.grad(bound_f, argnums=(1,))(*args[:-1])[0]
        g_r = jax.grad(bound_r, argnums=(1,))(*args[:-1])[0]
        np.testing.assert_allclose(np.asarray(g_f), np.asarray(g_r),
                                   rtol=1e-4, atol=1e-5)

    @pytest.mark.parametrize("block_k", [1, 2, 3, 8])
    def test_blocked_scan_bitwise_vs_reference(self, block_k):
        """The hand-blocked scan re-runs the identical per-slab math: its
        forward must be BITWISE equal to the one-shot composition."""
        args = _mk(7, 6, 8, 16, 130)
        want = hl._reference_impl(*args)
        got = hl._blocked_scan_impl(*args, block_k=block_k)
        assert np.array_equal(np.asarray(got), np.asarray(want))

    def test_blocked_scan_grads_match(self):
        args = _mk(7, 6, 8, 16, 12)
        x = args[-1]

        def loss_s(*ps):
            return jnp.sum(hl._blocked_scan_impl(*ps, x, block_k=2) ** 2)

        def loss_r(*ps):
            return jnp.sum(hl._reference_impl(*ps, x) ** 2)

        g_s = jax.grad(loss_s, argnums=tuple(range(7)))(*args[:-1])
        g_r = jax.grad(loss_r, argnums=tuple(range(7)))(*args[:-1])
        for a, w in zip(g_s, g_r):
            np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                       rtol=1e-5, atol=1e-5)

    def test_bf16_compute_dtype_parity(self):
        """bf16 operand casts inside the kernel mirror mlp.dense_apply's
        bf16 matmuls: fused output tracks the bf16 reference composition."""
        args = _mk(5, 6, 8, 16, 12)
        want = hl._reference_impl(*args, compute_dtype="bfloat16")
        got = hl._fwd_pallas(*args, tk=5, tb=6, interpret=True,
                             compute_dtype="bfloat16")
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-2, atol=2e-2)


class TestSelection:
    def test_block_estimates_flagship_and_eval_shapes(self):
        # flagship train (k=50, B=100, H1=100, hid=200, 784 px): the fwd
        # tile is the full batch; the larger bwd working set does not fit
        # at any legal tile -> backward falls back to XLA
        assert hl.select_block(50, 100, 100, 200, 784) == (8, 100)
        assert hl.select_block(50, 100, 100, 200, 784, grad=True) is None
        # the batch-500 eval shape the k-only predecessor had to reject
        # entirely now runs fused through a PARTIAL batch tile
        assert hl.select_block(250, 500, 100, 200, 784) == (8, 128)

    def test_env_forced_paths_bitwise_identical(self, monkeypatch, rng):
        cfg_f = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                            n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                            likelihood="logits", fused_likelihood=True)
        cfg_p = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                            n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                            likelihood="logits")
        params = init_params(rng, cfg_p)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (6, 12)) > 0.5
             ).astype(jnp.float32)
        key = jax.random.PRNGKey(2)
        want = log_weights(params, cfg_p, key, x, k=4)
        for path in ("reference", "blocked_scan", "pallas"):
            monkeypatch.setenv("IWAE_HOT_LOOP_PATH", path)
            got = log_weights(params, cfg_f, key, x, k=4)
            assert np.array_equal(np.asarray(got), np.asarray(want)), path

    def test_auto_on_cpu_selects_reference(self, monkeypatch):
        monkeypatch.delenv("IWAE_HOT_LOOP_PATH", raising=False)
        assert hl.select_path(4, 6, 4, 16, 12, on_tpu=False)[0] == "reference"

    def test_auto_scan_threshold(self, monkeypatch):
        monkeypatch.setenv("IWAE_HOT_LOOP_SCAN_BYTES", "1")
        path, _ = hl.select_path(4, 6, 4, 16, 12, on_tpu=False)
        assert path == "blocked_scan"

    def test_invalid_path_env_raises(self, monkeypatch):
        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "mosaic")
        with pytest.raises(ValueError, match="IWAE_HOT_LOOP_PATH"):
            hl.select_path(4, 6, 4, 16, 12, on_tpu=False)

    def test_forced_pallas_without_tile_falls_back(self, monkeypatch):
        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "pallas")
        monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", "1")
        with pytest.warns(RuntimeWarning, match="no tile fits"):
            path, _ = hl.select_path(4, 6, 4, 16, 12, on_tpu=False)
        assert path == "blocked_scan"

    def test_probe_compile_failure_selects_fallback(self, monkeypatch):
        """A shape that passes the estimate but fails to compile must warn
        once, cache the verdict, and select the fallback — never crash the
        enclosing jit (the kernel_usable contract)."""
        calls = []

        def boom(*a, **kw):
            calls.append(a)
            raise RuntimeError("scoped vmem exceeded (simulated)")

        monkeypatch.setattr(hl, "_probe_cache", {})
        monkeypatch.setattr(hl, "_fwd_pallas", boom)
        monkeypatch.setattr(hl, "_bwd_pallas", boom)
        with pytest.warns(RuntimeWarning, match="failed to compile"):
            assert hl.kernel_usable_block(8, 4, 8, 16, 12,
                                          interpret=False) is None
        assert len(calls) == 1
        # cached: the second query neither warns nor re-probes
        assert hl.kernel_usable_block(8, 4, 8, 16, 12,
                                      interpret=False) is None
        assert len(calls) == 1

    def test_probe_cache_invalidated_by_budget_change(self, monkeypatch):
        calls = []

        def fake_probe(*a, **kw):
            calls.append(a)
            return True

        monkeypatch.setattr(hl, "_probe_cache", {})
        monkeypatch.setattr(hl, "_probe_compiles", fake_probe)
        monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", str(1 << 30))
        assert hl.kernel_usable_block(8, 4, 8, 16, 12,
                                      interpret=False) is not None
        assert len(calls) == 1
        assert hl.kernel_usable_block(8, 4, 8, 16, 12,
                                      interpret=False) is not None
        assert len(calls) == 1
        monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", str((1 << 30) + 1))
        assert hl.kernel_usable_block(8, 4, 8, 16, 12,
                                      interpret=False) is not None
        assert len(calls) == 2


class TestFallbackTraining:
    """Satellite: force the VMEM gate shut and pin the blocked-scan path's
    losses + recompile behavior."""

    def _cfgs(self):
        cfg_f = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                            n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                            likelihood="logits", fused_likelihood=True)
        cfg_p = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                            n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                            likelihood="logits")
        return cfg_f, cfg_p

    def test_blocked_scan_losses_bit_identical(self, monkeypatch, rng):
        """fits_vmem forced to fail (budget=1) with pallas asked for ->
        blocked scan; the per-batch IWAE losses must be BIT-identical to
        the unfused reference model (same RNG, same per-row math)."""
        from iwae_replication_project_tpu.objectives import (
            ObjectiveSpec, objective_value_and_grad)

        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "pallas")
        monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", "1")
        cfg_f, cfg_p = self._cfgs()
        params = init_params(rng, cfg_p)
        spec = ObjectiveSpec("IWAE", k=4)
        for i in range(3):
            key = jax.random.fold_in(jax.random.PRNGKey(7), i)
            x = (jax.random.uniform(key, (6, 12)) > 0.5).astype(jnp.float32)
            with pytest.warns(RuntimeWarning, match="no tile fits"):
                bound_f, grads_f = objective_value_and_grad(
                    spec, params, cfg_f, key, x)
            bound_p, grads_p = objective_value_and_grad(
                spec, params, cfg_p, key, x)
            assert float(bound_f) == float(bound_p)  # bit-identical losses
            for a, w in zip(jax.tree.leaves(grads_f),
                            jax.tree.leaves(grads_p)):
                np.testing.assert_allclose(np.asarray(a), np.asarray(w),
                                           rtol=1e-5, atol=1e-6)

    def test_fallback_causes_zero_extra_recompiles(self, monkeypatch, rng):
        """Path selection is trace-time static: re-dispatching the compiled
        program under the forced fallback never re-enters XLA."""
        from iwae_replication_project_tpu.utils.compile_cache import (
            aot_call, cache_stats, isolated_aot_registry, stats_delta)

        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "blocked_scan")
        cfg_f, _ = self._cfgs()
        params = init_params(rng, cfg_f)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (6, 12)) > 0.5
             ).astype(jnp.float32)
        key = jax.random.PRNGKey(2)

        @jax.jit
        def loss(p, key, x):
            return -jnp.mean(log_weights(p, cfg_f, key, x, 4))

        with isolated_aot_registry():
            s0 = cache_stats()
            first = aot_call("hot_loop_fallback_loss", loss, (params, key, x))
            d1 = stats_delta(s0)
            assert d1["aot_misses"] == 1
            s1 = cache_stats()
            second = aot_call("hot_loop_fallback_loss", loss,
                              (params, key, x))
            d2 = stats_delta(s1)
            assert d2["aot_misses"] == 0            # warm hit
            assert d2["persistent_cache_misses"] == 0  # zero recompiles
        assert float(first) == float(second)


class TestTelemetry:
    def test_selection_records_gauge_and_counters(self, monkeypatch):
        from iwae_replication_project_tpu.telemetry.registry import (
            get_registry)

        before = hl.path_counters().get("blocked_scan", 0)
        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "blocked_scan")
        args = _mk(4, 6, 8, 16, 12)
        out = {"l1": {"w": args[1], "b": args[2]},
               "l2": {"w": args[3], "b": args[4]},
               "out": {"w": args[5], "b": args[6]}}
        hl.decoder_score(out, args[-1], args[0], on_tpu=False)
        assert hl.path_counters()["blocked_scan"] == before + 1
        assert hl.selected_path_code() == float(
            hl.PATH_CODES["blocked_scan"])
        # the pallas selection times its probe under a span/kernel/ name
        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "pallas")
        hl.decoder_score(out, args[-1], args[0], on_tpu=False)
        snap = get_registry().snapshot()
        assert "span/kernel/select/pallas" in snap["histograms"]

    def test_serving_metrics_expose_kernel_path(self):
        from iwae_replication_project_tpu.serving.metrics import (
            ServingMetrics)

        m = ServingMetrics()
        assert m.snapshot()["kernel_path"] == 0
        assert m.flat()["kernel_path"] == 0.0


class TestModelIntegration:
    def test_eval_row_stamps_kernel_path(self, rng):
        from iwae_replication_project_tpu.evaluation.metrics import (
            training_statistics)

        cfg = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                          n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                          likelihood="logits", fused_likelihood=True)
        params = init_params(rng, cfg)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (8, 12)) > 0.5
             ).astype(jnp.float32)
        acc, _ = training_statistics(params, cfg, jax.random.PRNGKey(2), x,
                                     k=4, batch_size=4, nll_k=8, nll_chunk=4,
                                     activity_samples=8,
                                     include_pruned_nll=False)
        assert acc["kernel_path"] in {float(v) for v in hl.PATH_CODES.values()}

    def test_eval_stamp_immune_to_unrelated_selections(self, monkeypatch,
                                                       rng):
        """The row stamp must describe the row's OWN config, not whichever
        program traced last (a jit-cache-hit dispatch traces nothing, so a
        last-trace gauge would misattribute it)."""
        from iwae_replication_project_tpu.evaluation.metrics import (
            training_statistics)

        # poison the last-trace gauge with a blocked_scan selection from an
        # unrelated shape
        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "blocked_scan")
        args = _mk(4, 6, 8, 16, 12)
        out = {"l1": {"w": args[1], "b": args[2]},
               "l2": {"w": args[3], "b": args[4]},
               "out": {"w": args[5], "b": args[6]}}
        hl.decoder_score(out, args[-1], args[0], on_tpu=False)
        assert hl.selected_path_code() == float(
            hl.PATH_CODES["blocked_scan"])
        monkeypatch.delenv("IWAE_HOT_LOOP_PATH")

        # an UNFUSED config's eval row must still stamp reference
        cfg = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                          n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                          likelihood="logits")
        params = init_params(rng, cfg)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (8, 12)) > 0.5
             ).astype(jnp.float32)
        acc, _ = training_statistics(params, cfg, jax.random.PRNGKey(2), x,
                                     k=4, batch_size=4, nll_k=8, nll_chunk=4,
                                     activity_samples=8,
                                     include_pruned_nll=False)
        assert acc["kernel_path"] == float(hl.PATH_CODES["reference"])

    def test_path_code_for_model_matches_dispatch(self, monkeypatch):
        cfg = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                          n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                          likelihood="logits", fused_likelihood=True)
        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "blocked_scan")
        assert hl.path_code_for_model(cfg, 4, 6, on_tpu=False) == float(
            hl.PATH_CODES["blocked_scan"])
        monkeypatch.delenv("IWAE_HOT_LOOP_PATH")
        assert hl.path_code_for_model(cfg, 4, 6, on_tpu=False) == float(
            hl.PATH_CODES["reference"])
        # unfused config -> reference regardless of environment
        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "pallas")
        cfg_u = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                            n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                            likelihood="logits")
        assert hl.path_code_for_model(cfg_u, 4, 6, on_tpu=False) == float(
            hl.PATH_CODES["reference"])

    def test_flops_accounting_matches_flagship_table(self):
        """utils/flops derives the r05 hard-coded flagship numbers exactly."""
        from iwae_replication_project_tpu.utils import flops

        cfg = ModelConfig.two_layer(likelihood="logits")
        no_k, per_k = flops.per_row_macs(cfg)
        assert no_k == 784 * 200 + 200 * 200 + 2 * 200 * 100
        assert per_k == ((100 * 100 + 100 * 100 + 2 * 100 * 50)
                         + (50 * 100 + 100 * 100 + 2 * 100 * 100)
                         + (100 * 200 + 200 * 200 + 200 * 784))
        assert flops.train_step_flops(cfg, 100, 50) == 3.0 * 2.0 * (
            100 * no_k + 100 * 50 * per_k)

    def test_peak_flops_table_detection(self):
        from iwae_replication_project_tpu.utils.flops import (
            peak_flops_for_kind)

        assert peak_flops_for_kind("TPU v5 lite")[0] == 197e12
        assert peak_flops_for_kind("TPU v5p")[0] == 459e12
        assert peak_flops_for_kind("TPU v4")[0] == 275e12
        assert peak_flops_for_kind("TPU v6e")[0] == 918e12
        peak, source = peak_flops_for_kind("warp drive 9000")
        assert peak is None and "warp drive 9000" in source


class TestServingGate:
    """serving_select_path / serving_kernel_usable — the lifted pin's
    trace-outside resolution (ISSUE 12)."""

    def test_auto_off_tpu_is_reference(self):
        assert hl.serving_select_path(4, 8, 10, 16, 20,
                                      on_tpu=False) == ("reference", None)

    def test_force_paths(self):
        assert hl.serving_select_path(4, 8, 10, 16, 20, on_tpu=False,
                                      force="blocked_scan") == \
            ("blocked_scan", None)
        assert hl.serving_select_path(4, 8, 10, 16, 20, on_tpu=False,
                                      force="reference") == \
            ("reference", None)
        # forced pallas off-TPU: interpret mode, the estimate admits the
        # per-row (tk, 1) tile
        path, tile = hl.serving_select_path(4, 8, 10, 16, 20, on_tpu=False,
                                            force="pallas")
        assert path == "pallas" and tile == (4, 1)

    def test_force_validation(self):
        with pytest.raises(ValueError, match="force argument"):
            hl.serving_select_path(4, 8, 10, 16, 20, on_tpu=False,
                                   force="mosaic")

    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("IWAE_HOT_LOOP_PATH", "blocked_scan")
        assert hl.serving_select_path(4, 8, 10, 16, 20,
                                      on_tpu=False)[0] == "blocked_scan"

    def test_scan_threshold_applies_to_bucket_workset(self, monkeypatch):
        # the whole-bucket working set k * rows * (2*hid + pix) decides
        # the scan threshold, mirroring select_path's auto rule
        monkeypatch.setenv("IWAE_HOT_LOOP_SCAN_BYTES", "1000")
        assert hl.serving_select_path(64, 64, 10, 16, 20,
                                      on_tpu=False)[0] == "blocked_scan"

    def test_oversized_row_tile_rejected(self, monkeypatch):
        # a per-row tile that cannot fit the budget -> None -> fallback
        monkeypatch.setenv("IWAE_FUSED_VMEM_BUDGET", "1")
        assert hl.serving_kernel_usable(8, 4, 10, 16, 20,
                                        interpret=True) is None
        with pytest.warns(RuntimeWarning, match="no tile fits"):
            path, _ = hl.serving_select_path(8, 4, 10, 16, 20,
                                             on_tpu=False, force="pallas")
        assert path == "blocked_scan"  # forced-pallas fallback, loudly

    def test_tile_proposal_validated(self):
        # an admissible proposed tk is honored; garbage falls back to the
        # default K-slab
        assert hl.serving_kernel_usable(16, 4, 10, 16, 20, interpret=True,
                                        tile=(16, 1)) == (16, 1)
        assert hl.serving_kernel_usable(16, 4, 10, 16, 20, interpret=True,
                                        tile=(13, 7)) == (8, 1)


class TestForcedTileAndConfigPins:
    def test_select_path_force_tile(self):
        path, tile = hl.select_path(16, 130, 10, 16, 20, on_tpu=False,
                                    force="pallas", force_tile=(16, 128))
        assert (path, tile) == ("pallas", (16, 128))
        with pytest.raises(ValueError, match="not admissible"):
            hl.select_path(16, 130, 10, 16, 20, on_tpu=False,
                           force="pallas", force_tile=(13, 40))

    def test_model_config_pins_flow_to_dispatch(self, rng):
        cfg = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                          n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12,
                          likelihood="logits")
        cfg_pin = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                              n_hidden_dec=(16,), n_latent_dec=(12,),
                              x_dim=12, likelihood="logits",
                              fused_likelihood=True,
                              hot_loop_path="blocked_scan")
        assert hl.path_code_for_model(cfg_pin, 4, 6, on_tpu=False) == float(
            hl.PATH_CODES["blocked_scan"])
        params = init_params(rng, cfg)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (6, 12)) > 0.5
             ).astype(jnp.float32)
        key = jax.random.PRNGKey(2)
        from iwae_replication_project_tpu.models import log_weights
        want = log_weights(params, cfg, key, x, k=4)
        got = log_weights(params, cfg_pin, key, x, k=4)  # iwaelint: disable=key-reuse -- parity check deliberately replays the IDENTICAL key; only the dispatch pin differs
        assert np.array_equal(np.asarray(got), np.asarray(want))

    def test_model_config_pin_validation(self):
        kw = dict(n_hidden_enc=(16,), n_latent_enc=(4,), n_hidden_dec=(16,),
                  n_latent_dec=(12,), x_dim=12, likelihood="logits")
        with pytest.raises(ValueError, match="requires"):
            ModelConfig(hot_loop_path="pallas", **kw)
        with pytest.raises(ValueError, match="unknown hot_loop_path"):
            ModelConfig(fused_likelihood=True, hot_loop_path="mosaic", **kw)
        with pytest.raises(ValueError, match="hot_loop_tile requires"):
            ModelConfig(fused_likelihood=True,
                        hot_loop_path="blocked_scan",
                        hot_loop_tile=(8, 1), **kw)
        with pytest.raises(ValueError, match="two positive ints"):
            ModelConfig(fused_likelihood=True, hot_loop_path="pallas",
                        hot_loop_tile=(8, 0), **kw)
        # tiles normalize to hashable int tuples (jit-static requirement)
        cfg = ModelConfig(fused_likelihood=True, hot_loop_path="pallas",
                          hot_loop_tile=[8, 1], **kw)
        assert cfg.hot_loop_tile == (8, 1)
        hash(cfg)

    def test_tile_admissible(self):
        assert hl.tile_admissible(8, 128, 50, 300)
        assert hl.tile_admissible(8, 300, 50, 300)     # full batch
        assert hl.tile_admissible(4, 1, 4, 1)          # tk == k < 8
        assert not hl.tile_admissible(13, 128, 50, 300)
        assert not hl.tile_admissible(8, 40, 50, 300)  # partial non-128
        assert not hl.tile_admissible(0, 128, 50, 300)
        assert not hl.tile_admissible(16, 128, 4, 300)  # tk > max(k, 8)
