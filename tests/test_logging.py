"""utils/logging tests: TensorBoard wire-format ROUND-TRIP and flush policy.

The TensorBoard writer (utils/logging.py) hand-encodes the tfevents wire
format (length-prefixed masked-crc32c records of protobuf Event messages).
Until now only the encoder side existed — any framing/field bug would ship
files TensorBoard silently fails to read. The decoder here is written
independently (bit-by-bit CRC instead of table-driven, its own varint/field
walker) and re-parses the emitted bytes, so encoder and checker cannot share
a bug by construction.
"""

import json
import os
import struct

import pytest

from iwae_replication_project_tpu.telemetry import MetricRegistry, span
from iwae_replication_project_tpu.utils.logging import (
    MetricsLogger,
    TensorBoardWriter,
)


# ---------------------------------------------------------------------------
# independent tfevents decoder (bit-by-bit crc32c, own proto field walker)
# ---------------------------------------------------------------------------

def _crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    for b in data:
        crc ^= b
        for _ in range(8):
            crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
    return crc ^ 0xFFFFFFFF


def _masked(crc: int) -> int:
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _varint(buf: bytes, i: int):
    out = shift = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def _walk_fields(buf: bytes):
    """Yield (field_number, wire_type, value_bytes_or_int) over a message."""
    i = 0
    while i < len(buf):
        tag, i = _varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, i = _varint(buf, i)
        elif wire == 1:
            v, i = buf[i:i + 8], i + 8
        elif wire == 2:
            ln, i = _varint(buf, i)
            v, i = buf[i:i + ln], i + ln
        elif wire == 5:
            v, i = buf[i:i + 4], i + 4
        else:
            raise AssertionError(f"unexpected wire type {wire}")
        yield field, wire, v


def _parse_event(data: bytes) -> dict:
    ev = {}
    for field, wire, v in _walk_fields(data):
        if field == 1 and wire == 1:
            ev["wall_time"] = struct.unpack("<d", v)[0]
        elif field == 2 and wire == 0:
            ev["step"] = v
        elif field == 3 and wire == 2:
            ev["file_version"] = v.decode()
        elif field == 5 and wire == 2:          # Summary
            for f2, w2, value_msg in _walk_fields(v):
                assert (f2, w2) == (1, 2), "expected Summary.value"
                val = {}
                for f3, w3, leaf in _walk_fields(value_msg):
                    if f3 == 1 and w3 == 2:
                        val["tag"] = leaf.decode()
                    elif f3 == 2 and w3 == 5:
                        val["value"] = struct.unpack("<f", leaf)[0]
                ev.setdefault("values", []).append(val)
    return ev


def decode_tfevents(path: str):
    """Parse a tfevents file, VERIFYING the record framing (length header,
    both masked crc32c checksums) before decoding each Event."""
    raw = open(path, "rb").read()
    events, i = [], 0
    while i < len(raw):
        header = raw[i:i + 8]
        (ln,) = struct.unpack("<Q", header)
        (hcrc,) = struct.unpack("<I", raw[i + 8:i + 12])
        data = raw[i + 12:i + 12 + ln]
        (dcrc,) = struct.unpack("<I", raw[i + 12 + ln:i + 16 + ln])
        assert _masked(_crc32c(header)) == hcrc, "header crc mismatch"
        assert _masked(_crc32c(data)) == dcrc, "data crc mismatch"
        events.append(_parse_event(data))
        i += 16 + ln
    assert i == len(raw), "trailing garbage after the last record"
    return events


def _events_file(d: str) -> str:
    (name,) = [f for f in os.listdir(d) if f.startswith("events.out.tfevents.")]
    return os.path.join(d, name)


# ---------------------------------------------------------------------------
# round-trip
# ---------------------------------------------------------------------------

class TestTensorBoardRoundTrip:
    def test_writer_records_reparse(self, tmp_path):
        w = TensorBoardWriter(str(tmp_path))
        scalars = [("loss", 1.5, 1), ("loss", 0.75, 2),
                   ("diag/ess", 12.25, 2), ("neg", -3.0, 3)]
        for tag, v, step in scalars:
            w.scalar(tag, v, step)
        w.close()

        events = decode_tfevents(_events_file(str(tmp_path)))
        assert events[0]["file_version"] == "brain.Event:2"
        got = [(v["tag"], v["value"], ev.get("step", 0))
               for ev in events[1:] for v in ev["values"]]
        assert got == [(t, pytest.approx(v), s) for t, v, s in scalars]
        for ev in events:
            assert ev["wall_time"] > 0

    def test_metrics_logger_tb_matches_jsonl(self, tmp_path):
        logger = MetricsLogger(str(tmp_path), run_name="rt")
        logger.log({"NLL": 88.5, "IWAE": -88.25, "skipme": "not-a-number"},
                   step=7)
        logger.close()
        d = os.path.join(str(tmp_path), "rt")
        row = json.loads(open(os.path.join(d, "metrics.jsonl")).read())
        events = decode_tfevents(_events_file(d))
        tb = {v["tag"]: (v["value"], ev["step"])
              for ev in events[1:] for v in ev["values"]}
        assert set(tb) == {"NLL", "IWAE"}  # step/time/non-numeric excluded
        for tag, (val, step) in tb.items():
            assert val == pytest.approx(row[tag])
            assert step == row["step"] == 7

    def test_large_step_and_long_tag_varints(self, tmp_path):
        """Multi-byte varints (step > 2^28) and a >127-byte tag exercise the
        length-prefix continuation bits."""
        w = TensorBoardWriter(str(tmp_path))
        tag = "span/" + "x" * 150
        w.scalar(tag, 2.0, step=3_000_000_000)
        w.close()
        events = decode_tfevents(_events_file(str(tmp_path)))
        assert events[1]["step"] == 3_000_000_000
        assert events[1]["values"][0]["tag"] == tag


# ---------------------------------------------------------------------------
# flush policy
# ---------------------------------------------------------------------------

class TestFlushPolicy:
    def test_default_flushes_every_row(self, tmp_path):
        logger = MetricsLogger(str(tmp_path), run_name="r", tensorboard=False)
        logger.log({"a": 1.0}, step=1)
        path = os.path.join(str(tmp_path), "r", "metrics.jsonl")
        assert len(open(path).read().splitlines()) == 1  # on disk pre-close
        logger.close()

    def test_flush_every_defers_then_close_drains(self, tmp_path):
        logger = MetricsLogger(str(tmp_path), run_name="r", tensorboard=False,
                               flush_every=10)
        path = os.path.join(str(tmp_path), "r", "metrics.jsonl")
        for i in range(3):
            logger.log({"a": float(i)}, step=i)
        assert open(path).read() == ""       # buffered: nothing synced yet
        logger.close()
        rows = [json.loads(ln) for ln in open(path).read().splitlines()]
        assert [r["a"] for r in rows] == [0.0, 1.0, 2.0]

    def test_flush_every_cadence(self, tmp_path):
        logger = MetricsLogger(str(tmp_path), run_name="r", tensorboard=False,
                               flush_every=2)
        path = os.path.join(str(tmp_path), "r", "metrics.jsonl")
        logger.log({"a": 1.0}, step=1)
        assert open(path).read() == ""
        logger.log({"a": 2.0}, step=2)       # second row hits the cadence
        assert len(open(path).read().splitlines()) == 2
        logger.close()

    def test_flush_every_validated(self, tmp_path):
        with pytest.raises(ValueError, match="flush_every"):
            MetricsLogger(str(tmp_path), run_name="r", flush_every=0)

    def test_log_registry_row(self, tmp_path):
        reg = MetricRegistry()
        reg.counter("hits").inc(4)
        with span("stagetest", registry=reg):
            pass
        logger = MetricsLogger(str(tmp_path), run_name="r", tensorboard=False)
        logger.log_registry(reg, step=5)
        logger.close()
        row = json.loads(open(os.path.join(str(tmp_path), "r",
                                           "metrics.jsonl")).read())
        assert row["hits"] == 4.0
        assert row["span/stagetest/count"] == 1.0
        assert row["step"] == 5
