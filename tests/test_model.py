"""Model-core tests: shapes, RNG discipline, and density bookkeeping."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import (
    ModelConfig,
    init_params,
    encode,
    log_weights,
    log_weights_and_aux,
    generate_x,
    reconstruct_probs,
)
from iwae_replication_project_tpu.models.iwae import log_prior, log_px_given_h

CFG1 = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                   n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12)
CFG2 = ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                   n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=12)


def make_batch(rng, b=5, d=12):
    return (jax.random.uniform(rng, (b, d)) > 0.5).astype(jnp.float32)


@pytest.mark.parametrize("cfg", [CFG1, CFG2], ids=["L1", "L2"])
class TestShapes:
    def test_encode_shapes(self, rng, cfg):
        params = init_params(rng, cfg)
        x = make_batch(jax.random.PRNGKey(1))
        h, log_q, (mu, std) = encode(params, cfg, rng, x, k=7)
        assert len(h) == cfg.n_stochastic
        for i, hi in enumerate(h):
            assert hi.shape == (7, 5, cfg.n_latent_enc[i])
        assert log_q.shape == (7, 5)
        assert mu.shape[-1] == cfg.n_latent_enc[-1]

    def test_log_weights_shape_and_finite(self, rng, cfg):
        params = init_params(rng, cfg)
        x = make_batch(jax.random.PRNGKey(1))
        lw = log_weights(params, cfg, rng, x, k=7)
        assert lw.shape == (7, 5)
        assert np.all(np.isfinite(np.asarray(lw)))

    def test_generate_and_reconstruct(self, rng, cfg):
        params = init_params(rng, cfg)
        x = make_batch(jax.random.PRNGKey(1))
        probs = reconstruct_probs(params, cfg, rng, x)
        assert probs.shape == (1, 5, cfg.x_dim)
        assert np.all((np.asarray(probs) > 0) & (np.asarray(probs) < 1))
        h_top = jnp.zeros((3, 5, cfg.n_latent_enc[-1]))
        gen = generate_x(params, cfg, rng, h_top)
        assert gen.shape == (3, 5, cfg.x_dim)


class TestRngDiscipline:
    def test_same_key_reproducible(self, rng):
        params = init_params(rng, CFG2)
        x = make_batch(jax.random.PRNGKey(1))
        a = log_weights(params, CFG2, jax.random.PRNGKey(7), x, k=4)
        b = log_weights(params, CFG2, jax.random.PRNGKey(7), x, k=4)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_different_keys_differ(self, rng):
        params = init_params(rng, CFG2)
        x = make_batch(jax.random.PRNGKey(1))
        a = log_weights(params, CFG2, jax.random.PRNGKey(7), x, k=4)
        b = log_weights(params, CFG2, jax.random.PRNGKey(8), x, k=4)
        assert not np.allclose(np.asarray(a), np.asarray(b))

    def test_k_samples_independent(self, rng):
        # distinct k-slices must be distinct draws (fan-out really samples k times)
        params = init_params(rng, CFG1)
        x = make_batch(jax.random.PRNGKey(1))
        h, _, _ = encode(params, CFG1, rng, x, k=3)
        h1 = np.asarray(h[0])
        assert not np.allclose(h1[0], h1[1])


class TestDensities:
    def test_log_q_matches_manual(self, rng):
        """log_q from encode must equal re-evaluating the chain densities."""
        params = init_params(rng, CFG2)
        x = make_batch(jax.random.PRNGKey(1))
        h, log_q, _ = encode(params, CFG2, rng, x, k=3)

        from iwae_replication_project_tpu.models.mlp import stochastic_block_apply
        from iwae_replication_project_tpu.ops.distributions import normal_log_prob
        mu0, std0 = stochastic_block_apply(params["enc"][0], x, CFG2.std_floor)
        manual = jnp.sum(normal_log_prob(h[0], mu0, std0), axis=-1)
        mu1, std1 = stochastic_block_apply(params["enc"][1], h[0], CFG2.std_floor)
        manual += jnp.sum(normal_log_prob(h[1], mu1, std1), axis=-1)
        np.testing.assert_allclose(np.asarray(log_q), np.asarray(manual), rtol=1e-5)

    def test_log_weights_decomposition(self, rng):
        params = init_params(rng, CFG2)
        x = make_batch(jax.random.PRNGKey(1))
        lw, aux = log_weights_and_aux(params, CFG2, rng, x, k=3)
        recomposed = aux["log_prior"] + aux["log_px_given_h"] - aux["log_q"]
        np.testing.assert_allclose(np.asarray(lw), np.asarray(recomposed), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(aux["log_px_given_h"]),
            np.asarray(log_px_given_h(params, CFG2, x, aux["h"][0])), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(aux["log_prior"]),
            np.asarray(log_prior(params, CFG2, aux["h"])), rtol=1e-6)

    def test_likelihood_modes_close(self, rng):
        """clamp (reference-parity) vs exact-logits likelihoods agree closely."""
        params = init_params(rng, CFG1)
        x = make_batch(jax.random.PRNGKey(1))
        cfg_exact = ModelConfig(**{**CFG1.__dict__, "likelihood": "logits"})
        a = log_weights(params, CFG1, rng, x, k=4)
        b = log_weights(params, cfg_exact, rng, x, k=4)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


class TestConfigValidation:
    def test_mismatched_lists_raise(self):
        with pytest.raises(ValueError):
            ModelConfig(n_hidden_enc=(8, 8), n_latent_enc=(4,),
                        n_hidden_dec=(8,), n_latent_dec=(12,), x_dim=12)

    def test_wrong_output_dim_raises(self):
        with pytest.raises(ValueError):
            ModelConfig(n_hidden_enc=(8,), n_latent_enc=(4,),
                        n_hidden_dec=(8,), n_latent_dec=(10,), x_dim=12)

    def test_flagship_configs(self):
        assert ModelConfig.two_layer().n_stochastic == 2
        assert ModelConfig.one_layer().n_stochastic == 1
