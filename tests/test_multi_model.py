"""Multi-tenant executable store + model-aware serving stack (ISSUE 13).

Four layers, mirroring the PR's ownership chain:

* **store** (utils/compile_cache.ExecutableStore) — LRU eviction order
  under an explicit byte budget, pin-during-dispatch protection, budget
  accounting reconciling bit-exactly with ``static_cost_records()``, and
  the warm/cold tier contract: evict -> re-request -> a *readmit* with
  ZERO fresh XLA compiles (``persistent_cache_misses`` stays flat);
* **engine** — the ``model`` label keys store entries per tenant and an
  unknown model at ``submit`` is the typed ``bad_request`` (ValueError);
* **router/wire** — model capability snapshots, model-affinity routing
  (fake engines, no device), default-model resolution in an all-labeled
  fleet, unknown-model rejection at the router AND over a live socket
  (typed response, connection survives), per-(client, model) quotas;
* **RemoteEngine** — a multi-model child tier's capability set rides the
  info handshake into a parent router, and ``model`` rides the wire.
"""

import threading
import time
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.utils import compile_cache as cc
from iwae_replication_project_tpu.utils.compile_cache import ExecutableStore


def _program(scale):
    """One tiny distinct jitted program per scale (distinct jaxprs, so
    distinct store entries with distinct persistent-cache keys)."""
    @jax.jit
    def f(x):
        return jnp.tanh(x * float(scale)).sum()

    return f


def _fill(store_models, x=None):
    """Admit one program per (name, model) via the module-level API (the
    store under test is the process default, isolated by the caller)."""
    x = x if x is not None else jnp.ones((16, 16))
    for i, (name, model) in enumerate(store_models):
        cc.aot_call(name, _program(i + 1), (x,), model=model)


# ---------------------------------------------------------------------------
# store: LRU / pins / budget / warm-cold tiers
# ---------------------------------------------------------------------------

class TestExecutableStore:
    def test_lru_eviction_order(self):
        """Under budget pressure the LEAST recently used entry goes first;
        a hit refreshes recency."""
        with cc.isolated_aot_registry(budget_bytes=None):
            s0 = cc.cache_stats()
            _fill([("p0", "a"), ("p1", "b"), ("p2", "c")])
            store = cc.executable_store()
            b0 = store.stats()["per_model"].get(
                "b", {}).get("evictions", 0)
            per = store.stats()["resident_bytes"] // 3
            # touch p0 -> MRU order is now p1, p2, p0
            cc.aot_call("p0", _program(1), (jnp.ones((16, 16)),), model="a")
            store.set_budget(2 * per + per // 2)     # fits two of three
            names = [k[1] for k in store.keys()]
            assert names == ["p2", "p0"], \
                f"LRU (p1) should have been evicted first, kept {names}"
            d = cc.stats_delta(s0)
            assert d["store_evictions"] == 1
            assert store.stats()["per_model"]["b"]["evictions"] == b0 + 1

    def test_pinned_entry_never_evicted_mid_dispatch(self):
        """A pinned entry survives any budget squeeze; release makes it
        evictable again (the engine pins for each in-flight dispatch)."""
        with cc.isolated_aot_registry(budget_bytes=None):
            store = cc.executable_store()
            _fill([("p0", "a")])
            pin = store.pin_prefix("a", "p0", ())
            _fill([("p1", "b")])
            per = store.stats()["resident_bytes"] // 2
            store.set_budget(per // 2)       # fits NOTHING unpinned
            assert [k[1] for k in store.keys()] == ["p0"], \
                "pinned entry was evicted mid-dispatch"
            pin.release()                    # release triggers re-eviction
            assert store.keys() == [], "released entry not reclaimed"

    def test_budget_accounting_reconciles_with_static_cost_records(self):
        """Every entry's budget bill is exactly its static cost record's
        ``peak_bytes`` (arg-bytes fallback when the stamp is off), so the
        store's resident_bytes is the sum over static_cost_records()."""
        with cc.isolated_aot_registry(budget_bytes=None):
            _fill([("p0", "a"), ("p1", "a"), ("p2", "b")])
            expected = 0
            for name, build_key, sig, cost in cc.static_cost_records():
                if cost is not None and cost.get("peak_bytes"):
                    expected += int(cost["peak_bytes"])
                else:
                    expected += cc._signature_arg_bytes(sig)
            st = cc.store_stats()
            assert st["resident_bytes"] == expected
            # per-model residency sums to the same total (counters are
            # process-global/monotonic, so restrict to live entries)
            resident = {m: d["resident_bytes"]
                        for m, d in st["per_model"].items()
                        if d["entries"] > 0}
            assert sum(resident.values()) == expected
            assert set(resident) == {"a", "b"}

    def test_evict_readmit_zero_fresh_compiles(self):
        """The acceptance pin: evict -> re-request -> the entry READMITS
        (counted) with zero fresh XLA compiles — the compile collapses to
        the warm layers under the store (persistent/in-memory cache)."""
        with cc.isolated_aot_registry(budget_bytes=None):
            store = cc.executable_store()
            x = jnp.ones((16, 16))
            s_pre = cc.cache_stats()
            _fill([("p0", "a"), ("p1", "b")], x=x)
            ref = float(cc.aot_call("p0", _program(1), (x,), model="a"))
            per = store.stats()["resident_bytes"] // 2
            store.set_budget(per + per // 2)         # fits one of the two
            d_evict = cc.stats_delta(s_pre)
            assert d_evict["store_evictions"] == 1
            assert d_evict["store_demotions"] == 1
            assert [k[1] for k in store.keys()] == ["p0"]
            s0 = cc.cache_stats()
            out = float(cc.aot_call("p1", _program(2), (x,), model="b"))
            d = cc.stats_delta(s0)
            assert d["persistent_cache_misses"] == 0, \
                f"readmit was a fresh XLA compile: {d}"
            assert d["store_readmits"] == 1 and d["store_misses"] == 1
            # and the readmitted program computes the same bits
            assert out == float(_program(2)(x))
            assert ref == float(_program(1)(x))

    def test_oversized_entry_still_admitted(self):
        """An entry larger than the whole budget is admitted (refusing
        would refuse to serve) and everything else unpinned is evicted."""
        with cc.isolated_aot_registry(budget_bytes=1):
            _fill([("p0", "a")])
            st = cc.store_stats()
            assert st["entries"] in (0, 1)   # admitted, then LRU-evictable
            # the call itself succeeded and returned a real result — the
            # budget never refuses service
            out = cc.aot_call("p0", _program(1), (jnp.ones((16, 16)),),
                              model="a")
            assert np.isfinite(float(out))

    def test_store_counters_exported_in_cache_stats(self):
        with cc.isolated_aot_registry(budget_bytes=None):
            s0 = cc.cache_stats()
            for key in ("store_hits", "store_misses", "store_evictions",
                        "store_demotions", "store_readmits",
                        "store_resident_bytes", "store_budget_bytes"):
                assert key in s0, key
            _fill([("p0", "a")])
            cc.aot_call("p0", _program(1), (jnp.ones((16, 16)),), model="a")
            d = cc.stats_delta(s0)
            assert d["store_misses"] == 1 and d["store_hits"] == 1

    def test_isolated_registry_budget_restored(self):
        before = cc.executable_store().budget_bytes
        with cc.isolated_aot_registry(budget_bytes=12345):
            assert cc.executable_store().budget_bytes == 12345
        assert cc.executable_store().budget_bytes == before


# ---------------------------------------------------------------------------
# engine boundary
# ---------------------------------------------------------------------------

def _tiny_engine(model=None, **kw):
    from iwae_replication_project_tpu.models import iwae as m
    from iwae_replication_project_tpu.serving import ServingEngine

    D = 16
    cfg = m.ModelConfig(x_dim=D, n_hidden_enc=(8,), n_latent_enc=(4,),
                        n_hidden_dec=(8,), n_latent_dec=(D,))
    params = m.init_params(jax.random.PRNGKey(0), cfg)
    return ServingEngine(params=params, model_config=cfg, k=3, max_batch=4,
                         model=model, **kw)


class TestEngineModelBoundary:
    def test_unknown_model_typed_bad_request(self):
        eng = _tiny_engine(model="m-a")
        with pytest.raises(ValueError, match="unknown model"):
            eng.submit("score", [0.0] * 16, model="m-b")
        # and nothing was enqueued: the reject is synchronous
        assert eng.metrics.snapshot()["counters"]["submitted"] == 0

    def test_unlabeled_engine_rejects_named_model(self):
        eng = _tiny_engine(model=None)
        with pytest.raises(ValueError, match="no named models"):
            eng.submit("score", [0.0] * 16, model="m-a")

    def test_own_model_accepted_and_store_entries_labeled(self):
        with cc.isolated_aot_registry():
            eng = _tiny_engine(model="m-a")
            out = eng.score(np.zeros((2, 16), np.float32))
            assert out.shape == (2,)
            models = {e["model"] for e in cc.executable_store().entries()}
            assert models == {"m-a"}
            # explicit own-model submits serve normally
            f = eng.submit("score", [0.0] * 16, model="m-a")
            eng.flush()
            assert np.isfinite(f.result())

    def test_sharded_engine_model_boundary(self):
        """The mesh-backed large-k engine inherits the whole model
        contract: label threading, store-entry attribution, and the typed
        unknown-model bad_request at submit."""
        from iwae_replication_project_tpu.models import iwae as m
        from iwae_replication_project_tpu.parallel.mesh import make_mesh
        from iwae_replication_project_tpu.serving.sharded import (
            ShardedScoreEngine)

        D = 16
        cfg = m.ModelConfig(x_dim=D, n_hidden_enc=(8,), n_latent_enc=(4,),
                            n_hidden_dec=(8,), n_latent_dec=(D,))
        params = m.init_params(jax.random.PRNGKey(0), cfg)
        with cc.isolated_aot_registry():
            eng = ShardedScoreEngine(params=params, model_config=cfg,
                                     mesh=make_mesh(dp=1, sp=1), k=2,
                                     k_chunk=2, k_max=8, max_batch=2,
                                     model="m-sharded")
            assert eng.model == "m-sharded"
            assert eng.models == frozenset({"m-sharded"})
            with pytest.raises(ValueError, match="unknown model"):
                eng.submit("score", [0.0] * D, model="m-other")
            out = eng.score(np.zeros((2, D), np.float32), k=5)
            assert out.shape == (2,)
            models = {e["model"] for e in cc.executable_store().entries()}
            assert models == {"m-sharded"}

    def test_per_model_latency_labels(self):
        eng = _tiny_engine(model="m-a")
        eng.score(np.zeros((2, 16), np.float32))
        snap = eng.metrics.snapshot()
        assert snap["model"] == "m-a"
        assert any(key.startswith("m-a/score/") for key in snap["latency"])
        flat = eng.metrics.flat()
        assert any(key.startswith("latency/m-a/score/") for key in flat)
        # the unlabeled engine keeps the historical schema
        eng2 = _tiny_engine()
        eng2.score(np.zeros((1, 16), np.float32))
        assert any(key.startswith("score/")
                   for key in eng2.metrics.snapshot()["latency"])


# ---------------------------------------------------------------------------
# router: capability snapshots + model routing (fakes, no device)
# ---------------------------------------------------------------------------

class ModelFakeEngine:
    """Minimal engine surface with a model label; results encode WHICH
    model served (seed*1000 + sum(row) + model tag) so misrouting is
    visible in the value, not just the counters."""

    def __init__(self, model, tag, dims=4):
        self.model = model
        self.models = frozenset({model})
        self.row_dims = {"score": dims}
        self.k = 5
        self.tag = tag
        self.submitted = []

    def submit(self, op, row, k=None, *, seed=None, model=None):
        if model is not None and model != self.model:
            raise ValueError(f"unknown model {model!r}")
        self.submitted.append((op, list(row), k, seed, model))
        f = Future()
        f.set_result(float(seed or 0) * 1000.0 + float(sum(row))
                     + self.tag)
        return f

    def start(self):
        pass

    def stop(self, timeout_s=None):
        pass

    def warmup(self, ops=(), ks=None):
        return {"programs": 0.0}


class TestRouterModelRouting:
    def _router(self):
        from iwae_replication_project_tpu.serving.frontend import (
            ReplicaRouter)

        ea = ModelFakeEngine("m-a", tag=0.25)
        eb = ModelFakeEngine("m-b", tag=0.5)
        return ReplicaRouter([ea, eb]), ea, eb

    def test_model_routes_to_declaring_replica(self):
        router, ea, eb = self._router()
        fa = router.submit("score", [1.0] * 4, model="m-a")
        fb = router.submit("score", [1.0] * 4, model="m-b")
        assert fa.result(timeout=5) != fb.result(timeout=5)
        assert len(ea.submitted) == 1 and len(eb.submitted) == 1
        assert ea.submitted[0][4] == "m-a"

    def test_unknown_model_synchronous_bad_request(self):
        router, ea, eb = self._router()
        with pytest.raises(ValueError, match="unknown model"):
            router.submit("score", [1.0] * 4, model="nope")
        assert router.outstanding == 0   # nothing leaked past the reject
        assert not ea.submitted and not eb.submitted

    def test_default_model_resolution_is_deterministic(self):
        """Model-less requests in an all-labeled fleet pin to the FIRST
        replica's model at admission — replica choice can never pick the
        weights."""
        router, ea, eb = self._router()
        assert router.default_model == "m-a"
        for _ in range(4):
            router.submit("score", [1.0] * 4).result(timeout=5)
        assert len(ea.submitted) == 4 and not eb.submitted
        assert all(s[4] == "m-a" for s in ea.submitted)

    def test_affinity_keyed_per_model(self):
        """Same (op, k) under different models are different affinity
        groups — each sticks to its own replica."""
        router, ea, eb = self._router()
        for _ in range(3):
            router.submit("score", [1.0] * 4, k=5, model="m-a")
            router.submit("score", [1.0] * 4, k=5, model="m-b")
        assert len(ea.submitted) == 3 and len(eb.submitted) == 3

    def test_mixed_labeled_unlabeled_fleet(self):
        """Unlabeled replicas keep serving model-less traffic (legacy);
        labeled traffic only lands on its model's replicas."""
        from iwae_replication_project_tpu.serving.frontend import (
            ReplicaRouter)

        class Unlabeled(ModelFakeEngine):
            def __init__(self):
                super().__init__("ignored", tag=0.125)
                self.model = None
                self.models = None

            def submit(self, op, row, k=None, *, seed=None, model=None):
                assert model is None, "unlabeled replica got a model tag"
                return super().submit(op, row, k, seed=seed, model=None)

        legacy = Unlabeled()
        ea = ModelFakeEngine("m-a", tag=0.25)
        router = ReplicaRouter([legacy, ea])
        router.submit("score", [1.0] * 4).result(timeout=5)       # legacy
        router.submit("score", [1.0] * 4, model="m-a").result(timeout=5)
        assert len(legacy.submitted) == 1 and len(ea.submitted) == 1


# ---------------------------------------------------------------------------
# per-(client, model) quotas
# ---------------------------------------------------------------------------

class TestPerClientModelQuotas:
    def test_model_lanes_are_isolated(self):
        from iwae_replication_project_tpu.serving.frontend import (
            ClientQuotas, QuotaExceeded, QuotaPolicy)

        clk = type("C", (), {"t": 0.0, "__call__": lambda s: s.t})()
        q = ClientQuotas(QuotaPolicy(rate=1.0, burst=2.0), clock=clk)
        q.admit("alice", 2, model="m-a")          # drains alice x m-a
        with pytest.raises(QuotaExceeded):
            q.admit("alice", 1, model="m-a")
        # same client, other model: full bucket — tenant lanes are isolated
        q.admit("alice", 2, model="m-b")
        # and the unlabeled lane is its own principal too
        q.admit("alice", 2)
        assert q.tokens("alice", model="m-a") == 0.0
        assert q.tokens("alice", model="m-b") == 0.0
        with pytest.raises(QuotaExceeded):
            q.admit("alice", 1, model="m-b")
        q.refund("alice", 1, model="m-b")
        q.admit("alice", 1, model="m-b")
        assert sorted(q.clients()) == ["alice"]


# ---------------------------------------------------------------------------
# wire boundary + RemoteEngine capability forwarding (real sockets, fakes)
# ---------------------------------------------------------------------------

class TestWireAndRemote:
    def _tier(self, **kw):
        from iwae_replication_project_tpu.serving.frontend import ServingTier

        ea = ModelFakeEngine("m-a", tag=0.25)
        eb = ModelFakeEngine("m-b", tag=0.5)
        tier = ServingTier([ea, eb], port=0, **kw)
        tier.start()
        return tier, ea, eb

    def test_unknown_model_typed_response_connection_survives(self):
        from iwae_replication_project_tpu.serving.frontend import TierClient
        from iwae_replication_project_tpu.serving.frontend.client import (
            TierError)

        tier, ea, eb = self._tier()
        try:
            with TierClient("127.0.0.1", tier.port) as cli:
                with pytest.raises(TierError) as ei:
                    cli.score([1.0] * 4, model="not-a-model")
                assert ei.value.code == "bad_request"
                assert "unknown model" in str(ei.value)
                # non-string model is equally typed, and the connection
                # still serves afterwards
                rid = cli.submit("score", [1.0] * 4, model=123)
                resp = cli.drain([rid])[rid]
                assert resp["ok"] is False
                assert resp["error"] == "bad_request"
                out = cli.score([1.0] * 4, model="m-b")
                assert out[0] == pytest.approx(4.5)   # m-b's tag
        finally:
            tier.stop(timeout_s=10)

    def test_info_declares_models(self):
        tier, _, _ = self._tier()
        try:
            info = tier.info()
            assert sorted(info["models"]) == ["m-a", "m-b"]
            assert info["default_model"] == "m-a"
            assert info["models"]["m-b"]["ops"] == ["score"]
            assert "store" in tier.stats()
        finally:
            tier.stop(timeout_s=10)

    def test_default_model_and_named_model_share_one_quota_lane(self):
        """The front end resolves a model-less request to the fleet's
        default model BEFORE quota admission, so omitting the field cannot
        mint a second (client, None) budget for the same weights."""
        from iwae_replication_project_tpu.serving.frontend import (
            QuotaPolicy, TierClient)
        from iwae_replication_project_tpu.serving.frontend.client import (
            TierError)

        tier, _, _ = self._tier(
            quota=QuotaPolicy(rate=0.001, burst=2.0))
        try:
            with TierClient("127.0.0.1", tier.port,
                            client_id="alice") as cli:
                cli.score([1.0] * 4)                    # lane (alice, m-a)
                cli.score([1.0] * 4, model="m-a")       # SAME lane
                with pytest.raises(TierError) as ei:
                    cli.score([1.0] * 4)                # lane exhausted
                assert ei.value.code == "quota_exceeded"
                # the other model's lane is untouched
                cli.score([1.0] * 4, model="m-b")
        finally:
            tier.stop(timeout_s=10)

    def test_remote_engine_forwards_model_capabilities(self):
        """A multi-model child tier proxies as ONE parent replica holding
        the whole zoo: capability set from the info handshake, unknown
        models rejected synchronously like the in-process engine."""
        from iwae_replication_project_tpu.serving.frontend import RemoteEngine

        tier, ea, eb = self._tier()
        try:
            proxy = RemoteEngine("127.0.0.1", tier.port)
            assert proxy.models == frozenset({"m-a", "m-b"})
            assert proxy.model == "m-a"
            with pytest.raises(ValueError, match="unknown model"):
                proxy.submit("score", [1.0] * 4, model="nope")
            proxy.close()
        finally:
            tier.stop(timeout_s=10)


def test_remote_engine_model_value_exact():
    """Split out: exact value math for the forwarded-model request (seed 0
    minted by the parent in admission order; the child tier re-admits with
    the explicit seed, so the fake computes 0*1000 + sum(row) + tag)."""
    from iwae_replication_project_tpu.serving.frontend import (
        RemoteEngine, ReplicaRouter, ServingTier)

    ea = ModelFakeEngine("m-a", tag=0.25)
    eb = ModelFakeEngine("m-b", tag=0.5)
    tier = ServingTier([ea, eb], port=0)
    tier.start()
    try:
        proxy = RemoteEngine("127.0.0.1", tier.port)
        parent = ReplicaRouter([proxy])
        out = parent.submit("score", [1.0] * 4,
                            model="m-b").result(timeout=5)
        assert out == pytest.approx(0 * 1000.0 + 4.0 + 0.5)
        assert eb.submitted and eb.submitted[0][4] == "m-b"
        assert not ea.submitted
        proxy.close()
    finally:
        tier.stop(timeout_s=10)
