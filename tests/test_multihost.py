"""Multi-host validation: the framework's SPMD programs over a mesh that
spans OS processes (SURVEY.md §2.5's distributed-communication row — the
multi-host layer on top of the fake-8-device single-process tests).

Two subprocesses with 4 virtual CPU devices each join a jax.distributed
cluster (tests/multihost_worker.py), build the same (dp=4, sp=2) mesh shape
the single-process suite uses, and run the whole-epoch scan plus a train
step fed host-locally through multihost.host_local_batch_to_global. Their
results must agree with each other AND with this (single-process,
8-device) run of the identical program.
"""

import json
import os
import socket
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import ModelConfig
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.parallel import (
    make_mesh,
    make_parallel_epoch_fn,
    make_parallel_train_step,
    multihost,
    shard_batch,
)
from iwae_replication_project_tpu.parallel.dp import replicate
from iwae_replication_project_tpu.training import create_train_state

CFG2 = ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                   n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=12)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_reference():
    """The same program the workers run, on this process's 8-device mesh."""
    mesh = make_mesh(dp=4, sp=2)
    spec = ObjectiveSpec("IWAE", k=8)
    state0 = create_train_state(jax.random.PRNGKey(0), CFG2)
    x = (jax.random.uniform(jax.random.PRNGKey(42), (32, 12)) > 0.5
         ).astype(jnp.float32)

    epoch = make_parallel_epoch_fn(spec, CFG2, mesh, n_train=32,
                                   batch_size=16, donate=False)
    s1, losses = epoch(replicate(mesh, state0), replicate(mesh, x))
    leafsum = float(sum(np.abs(np.asarray(l)).sum()
                        for l in jax.tree.leaves(s1.params)))

    step = make_parallel_train_step(spec, CFG2, mesh, donate=False,
                                    batch_size=16)
    _, metrics = step(replicate(mesh, state0), shard_batch(mesh, x[:16]))
    return np.asarray(losses), leafsum, float(metrics["loss"])


@pytest.mark.slow
def test_two_process_cluster_matches_single_process(devices, tmp_path):
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    # workers must not inherit this process's compilation-cache dir lock
    env["JAX_COMPILATION_CACHE_DIR"] = str(tmp_path / "mh_cache")

    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:  # no orphans on timeout / assertion failure
            if p.poll() is None:
                p.kill()

    # the cluster actually formed: 2 processes x 4 devices = 8 global
    for o in outs:
        assert o["info"]["process_count"] == 2
        assert o["info"]["local_device_count"] == 4
        assert o["info"]["global_device_count"] == 8

    # both processes computed identical results
    assert outs[0]["epoch_losses"] == outs[1]["epoch_losses"]
    assert outs[0]["leafsum"] == outs[1]["leafsum"]
    assert outs[0]["step_loss"] == outs[1]["step_loss"]

    # ... and they match the single-process run of the same program
    ref_losses, ref_leafsum, ref_step_loss = _single_process_reference()
    np.testing.assert_allclose(outs[0]["epoch_losses"], ref_losses, rtol=1e-6)
    np.testing.assert_allclose(outs[0]["leafsum"], ref_leafsum, rtol=1e-5)
    np.testing.assert_allclose(outs[0]["step_loss"], ref_step_loss, rtol=1e-6)


def test_fetch_and_info_single_process(devices):
    """multihost.fetch / process_info degrade gracefully in-process."""
    info = multihost.process_info()
    assert info["process_count"] == 1
    assert info["global_device_count"] == 8
    tree = {"a": jnp.ones((3,)), "b": 2.5}
    out = multihost.fetch(tree)
    np.testing.assert_array_equal(out["a"], np.ones((3,)))
    assert out["b"] == 2.5
