"""Multi-host validation: the framework's SPMD programs over a mesh that
spans OS processes (SURVEY.md §2.5's distributed-communication row — the
multi-host layer on top of the fake-8-device single-process tests).

Two subprocesses with 4 virtual CPU devices each join a jax.distributed
cluster (tests/multihost_worker.py), build the same (dp=4, sp=2) mesh shape
the single-process suite uses, and run the whole-epoch scan plus a train
step fed host-locally through multihost.host_local_batch_to_global. Their
results must agree with each other AND with this (single-process,
8-device) run of the identical program.
"""

import json
import os
import socket
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import ModelConfig
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.parallel import (
    make_mesh,
    make_parallel_epoch_fn,
    make_parallel_train_step,
    multihost,
    shard_batch,
)
from iwae_replication_project_tpu.parallel.dp import replicate
from iwae_replication_project_tpu.training import create_train_state

CFG2 = ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                   n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=12)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _single_process_reference():
    """The same program the workers run, on this process's 8-device mesh."""
    mesh = make_mesh(dp=4, sp=2)
    spec = ObjectiveSpec("IWAE", k=8)
    state0 = create_train_state(jax.random.PRNGKey(0), CFG2)
    x = (jax.random.uniform(jax.random.PRNGKey(42), (32, 12)) > 0.5
         ).astype(jnp.float32)

    epoch = make_parallel_epoch_fn(spec, CFG2, mesh, n_train=32,
                                   batch_size=16, donate=False)
    s1, losses = epoch(replicate(mesh, state0), replicate(mesh, x))
    leafsum = float(sum(np.abs(np.asarray(l)).sum()
                        for l in jax.tree.leaves(s1.params)))

    step = make_parallel_train_step(spec, CFG2, mesh, donate=False,
                                    batch_size=16)
    _, metrics = step(replicate(mesh, state0), shard_batch(mesh, x[:16]))

    from jax.sharding import NamedSharding, PartitionSpec as P

    from iwae_replication_project_tpu.parallel.eval import (
        make_parallel_dataset_scalars)
    from iwae_replication_project_tpu.parallel.mesh import AXES

    scal_fn = make_parallel_dataset_scalars(CFG2, mesh, k=8, nll_k=16,
                                            nll_chunk=8)
    batches = jax.device_put(x.reshape(2, 16, 12),
                             NamedSharding(mesh, P(None, AXES.dp)))
    scalars = np.asarray(scal_fn(s1.params, jax.random.PRNGKey(3), batches))
    return np.asarray(losses), leafsum, float(metrics["loss"]), scalars


@pytest.mark.slow
def test_two_process_cluster_matches_single_process(devices, tmp_path):
    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    # workers must not inherit this process's compilation-cache dir lock
    env["JAX_COMPILATION_CACHE_DIR"] = str(tmp_path / "mh_cache")

    procs = [subprocess.Popen(
        [sys.executable, worker, str(i), "2", str(port)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env)
        for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            outs.append(json.loads(out.strip().splitlines()[-1]))
    finally:
        for p in procs:  # no orphans on timeout / assertion failure
            if p.poll() is None:
                p.kill()

    # the cluster actually formed: 2 processes x 4 devices = 8 global
    for o in outs:
        assert o["info"]["process_count"] == 2
        assert o["info"]["local_device_count"] == 4
        assert o["info"]["global_device_count"] == 8

    # both processes computed identical results
    assert outs[0]["epoch_losses"] == outs[1]["epoch_losses"]
    assert outs[0]["leafsum"] == outs[1]["leafsum"]
    assert outs[0]["step_loss"] == outs[1]["step_loss"]
    assert outs[0]["eval_scalars"] == outs[1]["eval_scalars"]
    assert (outs[0]["eval_scalars_cross_sp"]
            == outs[1]["eval_scalars_cross_sp"])
    # collectives are placement-independent: the mesh whose sp pairs CROSS
    # the process boundary gives the same scalars (up to reduction-order
    # rounding) as the process-local-sp mesh
    np.testing.assert_allclose(outs[0]["eval_scalars_cross_sp"],
                               outs[0]["eval_scalars"], rtol=1e-5, atol=1e-6)

    # ... and they match the single-process run of the same program
    (ref_losses, ref_leafsum, ref_step_loss,
     ref_scalars) = _single_process_reference()
    np.testing.assert_allclose(outs[0]["epoch_losses"], ref_losses, rtol=1e-6)
    np.testing.assert_allclose(outs[0]["leafsum"], ref_leafsum, rtol=1e-5)
    np.testing.assert_allclose(outs[0]["step_loss"], ref_step_loss, rtol=1e-6)
    np.testing.assert_allclose(outs[0]["eval_scalars"], ref_scalars,
                               rtol=1e-5, atol=1e-6)


@pytest.mark.slow
def test_two_process_driver_run(devices, tmp_path, preempt_after):
    """The PRODUCTION driver end-to-end under --multihost: two processes run
    `experiment.main` against one shared config; the cluster forms inside
    run_experiment, the mesh defaults to all 8 global devices, only the
    primary writes metrics/figures/results, checkpoints are Orbax-coordinated,
    and the logged numbers match a single-process mesh run of the same
    config."""
    from iwae_replication_project_tpu.experiment import run_experiment
    from iwae_replication_project_tpu.utils.config import ExperimentConfig

    shared = dict(
        dataset="binarized_mnist", data_dir=str(tmp_path / "data"),
        n_hidden_encoder=(16,), n_hidden_decoder=(16,),
        n_latent_encoder=(4,), n_latent_decoder=(784,),
        loss_function="IWAE", k=4, batch_size=32, n_stages=2,
        eval_k=4, nll_k=8, nll_chunk=4, eval_batch_size=16,
        activity_samples=8, save_figures=True,  # exercises viz fetch on the
        # process-spanning mesh (primary-only)
    )
    cfg_path = tmp_path / "cfg.json"
    cfg_path.write_text(ExperimentConfig(**shared).to_json())

    port = _free_port()
    worker = os.path.join(os.path.dirname(__file__),
                          "multihost_driver_worker.py")
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = (os.path.dirname(os.path.dirname(__file__))
                         + os.pathsep + env.get("PYTHONPATH", ""))
    env["JAX_COMPILATION_CACHE_DIR"] = str(tmp_path / "mh_cache")

    def argv(i, coord_port, extra=()):
        return [sys.executable, worker, "--config", str(cfg_path),
                "--multihost", "--coordinator", f"127.0.0.1:{coord_port}",
                "--num-processes", "2", "--process-id", str(i),
                "--log-dir", str(tmp_path / "runs"),
                "--checkpoint-dir", str(tmp_path / "ckpt")] + list(extra)

    def run_pair(coord_port, extra=()):
        procs = [subprocess.Popen(argv(i, coord_port, extra),
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.PIPE, text=True, env=env)
                 for i in range(2)]
        outs = []
        try:
            for p in procs:
                out, err = p.communicate(timeout=420)
                assert p.returncode == 0, f"driver worker failed:\n{out}\n{err}"
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        return outs

    run_pair(port)

    # exactly ONE process wrote the run artifacts
    runs_dir = tmp_path / "runs"
    run_dirs = sorted(os.listdir(runs_dir))
    assert len(run_dirs) == 1, run_dirs
    metrics_path = runs_dir / run_dirs[0] / "metrics.jsonl"
    rows = [json.loads(l) for l in metrics_path.read_text().splitlines()]
    assert [r["stage"] for r in rows] == [1, 2]
    assert os.path.exists(runs_dir / run_dirs[0] / "results.pkl")
    assert os.path.exists(runs_dir / run_dirs[0] / "figures"
                          / "stage_01_samples.png")

    # the logged numbers match a single-process run of the same mesh shape
    ref_cfg = ExperimentConfig(**shared, mesh_dp=8,
                               log_dir=str(tmp_path / "ref_runs"),
                               checkpoint_dir=str(tmp_path / "ref_ckpt"))
    _, ref_hist = run_experiment(ref_cfg)
    for row, (ref_res, _) in zip(rows, ref_hist):
        for key in ("VAE", "IWAE", "NLL"):
            np.testing.assert_allclose(row[key], ref_res[key], rtol=1e-4,
                                       atol=1e-5)

    # multi-host RESUME: a second cluster run with one more stage restores
    # the Orbax checkpoint written by the first and continues at stage 3
    outs = run_pair(_free_port(), extra=["--n-stages", "3"])
    assert "resumed from checkpoint; continuing at stage 3" in outs[0]
    rows = [json.loads(l) for l in metrics_path.read_text().splitlines()]
    assert rows[-1]["stage"] == 3

    # multi-host MID-STAGE resume (round 5): a single-process dp=8 mesh run
    # with intra-stage checkpointing is killed right after an intra-stage
    # save (stage 3, 4 of 9 passes); the two-process cluster then restores
    # that checkpoint across the process-spanning mesh and finishes the
    # stage. Cross-topology restore is the point: the checkpoint's
    # fully-replicated arrays load into the cluster's sharded template.
    kill_cfg = ExperimentConfig(**{**shared, "n_stages": 3,
                                   "save_figures": False},
                                mesh_dp=8, checkpoint_every_passes=2,
                                log_dir=str(tmp_path / "kill_runs"),
                                checkpoint_dir=str(tmp_path / "kill_ckpt"))
    # 5th save = stage1-end, s2-p2, s2-end, s3-p2, s3-p4 -> mid-stage 3
    with pytest.raises(KeyboardInterrupt), preempt_after(5):
        run_experiment(kill_cfg)
    outs = run_pair(_free_port(), extra=[
        "--n-stages", "3", "--checkpoint-dir", str(tmp_path / "kill_ckpt"),
        "--log-dir", str(tmp_path / "kill_runs"), "--no-figures"])
    assert "continuing at stage 3, pass 5" in outs[0]
    kill_rows_path = (tmp_path / "kill_runs"
                      / os.listdir(tmp_path / "kill_runs")[0]
                      / "metrics.jsonl")
    last = json.loads(kill_rows_path.read_text().splitlines()[-1])
    assert last["stage"] == 3
    # the resumed cluster's stage-3 numbers track the uninterrupted
    # single-process 3-stage reference. NOT bit-tight: passes 5-9 of stage 3
    # ran on a different topology (2 processes) than the reference's, and
    # f32 collective-reduction order differs across topologies — the
    # per-step drift compounds over training (~2e-3 relative after 13
    # passes). Same-topology mid-stage resume IS bit-identical
    # (tests/test_experiment.py kill/resume, both variants); this section
    # certifies the cross-topology restore semantics, not bitwise numerics.
    ref3 = ExperimentConfig(**{**shared, "n_stages": 3,
                               "save_figures": False},
                            mesh_dp=8, resume=False,
                            log_dir=str(tmp_path / "ref3_runs"),
                            checkpoint_dir=str(tmp_path / "ref3_ckpt"))
    _, ref3_hist = run_experiment(ref3)
    for key in ("VAE", "IWAE", "NLL"):
        np.testing.assert_allclose(last[key], ref3_hist[-1][0][key],
                                   rtol=1e-2)


def test_fetch_and_info_single_process(devices):
    """multihost.fetch / process_info degrade gracefully in-process."""
    info = multihost.process_info()
    assert info["process_count"] == 1
    assert info["global_device_count"] == 8
    tree = {"a": jnp.ones((3,)), "b": 2.5}
    out = multihost.fetch(tree)
    np.testing.assert_array_equal(out["a"], np.ones((3,)))
    assert out["b"] == 2.5
