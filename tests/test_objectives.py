"""Estimator tests: the reference's mathematical identities as free oracles
(SURVEY.md §4): IWAE_1 == VAE_1, MIWAE edge cases, power_1 == IWAE, CIWAE
endpoints, analytic-vs-MC ELBO, monotonicity in k, and modified-gradient
estimator correctness (DReG/STL/PIWAE)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import ModelConfig, init_params, log_weights_and_aux
from iwae_replication_project_tpu.objectives import (
    ObjectiveSpec,
    alpha_bound,
    bound_from_log_weights,
    ciwae_bound,
    iwae_bound,
    median_bound,
    miwae_bound,
    objective_bound,
    objective_value_and_grad,
    power_bound,
    vae_bound,
)

CFG = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                  n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12)
CFG2 = ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                   n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=12)


@pytest.fixture
def log_w():
    return jnp.asarray(np.random.RandomState(0).randn(12, 5).astype(np.float32) * 3)


@pytest.fixture
def model_setup(rng):
    params = init_params(rng, CFG)
    x = (jax.random.uniform(jax.random.PRNGKey(1), (5, 12)) > 0.5).astype(jnp.float32)
    return params, x


class TestReducerIdentities:
    def test_iwae_k1_equals_vae(self):
        lw = jnp.asarray(np.random.RandomState(1).randn(1, 7).astype(np.float32))
        np.testing.assert_allclose(iwae_bound(lw), vae_bound(lw), rtol=1e-6)

    def test_miwae_edges(self, log_w):
        k = log_w.shape[0]
        np.testing.assert_allclose(miwae_bound(log_w, k2=1), iwae_bound(log_w), rtol=1e-6)
        np.testing.assert_allclose(miwae_bound(log_w, k2=k), vae_bound(log_w), rtol=1e-5)

    def test_power_p1_equals_iwae(self, log_w):
        np.testing.assert_allclose(power_bound(log_w, 1.0), iwae_bound(log_w), rtol=1e-6)

    def test_ciwae_endpoints(self, log_w):
        np.testing.assert_allclose(ciwae_bound(log_w, 1.0), vae_bound(log_w), rtol=1e-6)
        np.testing.assert_allclose(ciwae_bound(log_w, 0.0), iwae_bound(log_w), rtol=1e-6)

    def test_alpha1_equals_vae(self, log_w):
        recon = jnp.abs(log_w)
        np.testing.assert_allclose(alpha_bound(log_w, recon, 1.0), vae_bound(log_w), rtol=1e-6)

    def test_median_midpoint_even_k(self):
        lw = jnp.asarray(np.array([[1.0], [2.0], [10.0], [3.0]], np.float32))
        # midpoint of {2, 3} = 2.5 — matches tfp percentile 'midpoint' semantics
        np.testing.assert_allclose(median_bound(lw), 2.5, rtol=1e-6)

    def test_jensen_ordering(self, log_w):
        # VAE <= MIWAE <= IWAE <= logsumexp bound orderings from Jensen
        assert float(vae_bound(log_w)) <= float(miwae_bound(log_w, k2=4)) + 1e-5
        assert float(miwae_bound(log_w, k2=4)) <= float(iwae_bound(log_w)) + 1e-5

    def test_power_monotone_in_p(self, log_w):
        # power-mean inequality: higher p => higher bound value
        b = [float(power_bound(log_w, p)) for p in (0.5, 1.0, 2.0, 5.0)]
        assert all(b[i] <= b[i + 1] + 1e-5 for i in range(3))


class TestModelBounds:
    def test_v1_matches_mc_elbo(self, model_setup):
        """Analytic-KL ELBO vs MC ELBO (the reference's built-in oracle,
        flexible_IWAE.py:425)."""
        params, x = model_setup
        k = 4000
        key = jax.random.PRNGKey(3)
        mc = objective_bound(ObjectiveSpec("VAE", k=k), params, CFG, key, x)
        v1 = objective_bound(ObjectiveSpec("VAE_V1", k=k), params, CFG, key, x)
        np.testing.assert_allclose(float(mc), float(v1), atol=0.05)

    @pytest.mark.slow
    def test_iwae_monotone_in_k(self, model_setup):
        """E[L_{k}] nondecreasing in k (Burda Thm 1; PDF p.5 Eq. 3)."""
        params, x = model_setup
        bounds = []
        for k in (1, 5, 25, 125):
            vals = [float(objective_bound(ObjectiveSpec("IWAE", k=k), params, CFG,
                                          jax.random.PRNGKey(100 + r), x))
                    for r in range(20)]
            bounds.append(np.mean(vals))
        assert all(bounds[i] <= bounds[i + 1] + 0.05 for i in range(len(bounds) - 1))

    def test_dispatch_all_names(self, model_setup):
        params, x = model_setup
        key = jax.random.PRNGKey(5)
        for name in ("VAE", "IWAE", "VAE_V1", "L_alpha", "L_power_p", "L_median",
                     "CIWAE", "MIWAE", "PIWAE", "DReG", "STL"):
            spec = ObjectiveSpec(name, k=8, k2=2, p=2.0, alpha=0.5, beta=0.3)
            val = objective_bound(spec, params, CFG, key, x)
            assert np.isfinite(float(val)), name

    def test_aux_required_errors(self, log_w):
        with pytest.raises(ValueError):
            bound_from_log_weights(ObjectiveSpec("L_alpha", k=12), log_w, None)
        with pytest.raises(ValueError):
            bound_from_log_weights(ObjectiveSpec("VAE_V1", k=12), log_w, None)

    def test_spec_validation(self):
        with pytest.raises(ValueError):
            ObjectiveSpec("nope")
        with pytest.raises(ValueError):
            ObjectiveSpec("MIWAE", k=10, k2=3)

    def test_vae_v1_rejects_multilayer_models(self):
        """The reference marks get_L_V1 single-layer-only
        (flexible_IWAE.py:433); a 2-layer model must raise, not silently
        compute a wrong-by-construction 'analytic' bound."""
        from iwae_replication_project_tpu.models import ModelConfig, iwae as model

        cfg2 = ModelConfig(n_hidden_enc=(8, 8), n_latent_enc=(4, 2),
                           n_hidden_dec=(8, 8), n_latent_dec=(4, 12), x_dim=12)
        params = model.init_params(jax.random.PRNGKey(0), cfg2)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (6, 12)) > 0.5).astype(jnp.float32)
        with pytest.raises(ValueError, match="single-stochastic-layer"):
            objective_bound(ObjectiveSpec("VAE_V1", k=4), params, cfg2,
                            jax.random.PRNGKey(2), x)


class TestGradientEstimators:
    def test_standard_grad_matches_manual(self, model_setup):
        params, x = model_setup
        key = jax.random.PRNGKey(5)
        spec = ObjectiveSpec("IWAE", k=6)
        val, grads = objective_value_and_grad(spec, params, CFG, key, x)
        val2, grads2 = jax.value_and_grad(
            lambda p: objective_bound(spec, p, CFG, key, x))(params)
        np.testing.assert_allclose(float(val), float(val2), rtol=1e-6)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7),
                     grads, grads2)

    def test_stl_decoder_grad_matches_iwae(self, model_setup):
        """Score-stopping must not change decoder gradients."""
        params, x = model_setup
        key = jax.random.PRNGKey(5)
        _, g_stl = objective_value_and_grad(ObjectiveSpec("STL", k=6), params, CFG, key, x)
        _, g_iwae = objective_value_and_grad(ObjectiveSpec("IWAE", k=6), params, CFG, key, x)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
                     {"dec": g_stl["dec"], "out": g_stl["out"]},
                     {"dec": g_iwae["dec"], "out": g_iwae["out"]})

    def test_dreg_decoder_grad_matches_iwae(self, model_setup):
        params, x = model_setup
        key = jax.random.PRNGKey(5)
        _, g = objective_value_and_grad(ObjectiveSpec("DReG", k=6), params, CFG, key, x)
        _, g_iwae = objective_value_and_grad(ObjectiveSpec("IWAE", k=6), params, CFG, key, x)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
                     {"dec": g["dec"], "out": g["out"]},
                     {"dec": g_iwae["dec"], "out": g_iwae["out"]})

    def test_dreg_encoder_grad_differs(self, model_setup):
        params, x = model_setup
        key = jax.random.PRNGKey(5)
        _, g = objective_value_and_grad(ObjectiveSpec("DReG", k=6), params, CFG, key, x)
        _, g_iwae = objective_value_and_grad(ObjectiveSpec("IWAE", k=6), params, CFG, key, x)
        diffs = jax.tree.leaves(jax.tree.map(lambda a, b: float(jnp.max(jnp.abs(a - b))),
                                             g["enc"], g_iwae["enc"]))
        assert max(diffs) > 1e-6

    def test_piwae_grads_match_parts(self, model_setup):
        """PIWAE encoder grad == MIWAE grad's encoder part; decoder == IWAE's."""
        params, x = model_setup
        key = jax.random.PRNGKey(5)
        spec = ObjectiveSpec("PIWAE", k=6, k2=3)
        _, g = objective_value_and_grad(spec, params, CFG, key, x)
        _, g_miwae = jax.value_and_grad(
            lambda p: objective_bound(ObjectiveSpec("MIWAE", k=6, k2=3), p, CFG, key, x))(params)
        _, g_iwae = jax.value_and_grad(
            lambda p: objective_bound(ObjectiveSpec("IWAE", k=6), p, CFG, key, x))(params)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
                     g["enc"], g_miwae["enc"])
        jax.tree.map(lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6),
                     {"dec": g["dec"], "out": g["out"]},
                     {"dec": g_iwae["dec"], "out": g_iwae["out"]})

    def test_stl_unbiased_same_bound(self, model_setup):
        params, x = model_setup
        key = jax.random.PRNGKey(5)
        v_stl, _ = objective_value_and_grad(ObjectiveSpec("STL", k=6), params, CFG, key, x)
        v_iwae, _ = objective_value_and_grad(ObjectiveSpec("IWAE", k=6), params, CFG, key, x)
        np.testing.assert_allclose(float(v_stl), float(v_iwae), rtol=1e-6)

    @pytest.mark.slow
    def test_multilayer_gradients_finite(self, rng):
        params = init_params(rng, CFG2)
        x = (jax.random.uniform(jax.random.PRNGKey(1), (4, 12)) > 0.5).astype(jnp.float32)
        for name in ("IWAE", "DReG", "STL", "PIWAE", "MIWAE"):
            _, g = objective_value_and_grad(ObjectiveSpec(name, k=4, k2=2), params, CFG2,
                                            jax.random.PRNGKey(2), x)
            assert all(np.all(np.isfinite(np.asarray(l))) for l in jax.tree.leaves(g)), name
