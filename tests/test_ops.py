"""Unit tests for the numerics layer: closed-form log-probs and logsumexp.

Oracles: scipy-free closed forms computed in numpy float64, plus extreme-value
stability goldens (SURVEY.md §4 test plan).
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.ops import (
    bernoulli_log_prob,
    clamp_probs,
    logmeanexp,
    logsumexp,
    normal_kl_standard,
    normal_log_prob,
    normal_sample,
)
from iwae_replication_project_tpu.ops.logsumexp import (
    online_logsumexp_finalize,
    online_logsumexp_init,
    online_logsumexp_merge,
    online_logsumexp_update,
    streaming_logmeanexp,
)


def np_normal_logpdf(x, mu, std):
    return -0.5 * ((x - mu) / std) ** 2 - np.log(std) - 0.5 * math.log(2 * math.pi)


class TestNormal:
    def test_log_prob_matches_closed_form(self, rng):
        x = np.random.RandomState(0).randn(5, 7).astype(np.float32)
        mu = np.float32(0.3)
        std = np.float32(1.7)
        got = normal_log_prob(jnp.asarray(x), mu, std)
        np.testing.assert_allclose(got, np_normal_logpdf(x, mu, std), rtol=1e-5)

    def test_sample_moments_and_shape(self, rng):
        mu = jnp.array([1.0, -2.0])
        std = jnp.array([0.5, 2.0])
        s = normal_sample(rng, mu, std, sample_shape=(20000,))
        assert s.shape == (20000, 2)
        np.testing.assert_allclose(jnp.mean(s, axis=0), mu, atol=0.05)
        np.testing.assert_allclose(jnp.std(s, axis=0), std, atol=0.05)

    def test_sample_is_reparameterized(self, rng):
        # gradient of E[s] wrt mu must be 1 exactly (pathwise).
        g = jax.grad(lambda m: jnp.mean(normal_sample(rng, m, 1.0, (100,))))(0.0)
        np.testing.assert_allclose(g, 1.0, rtol=1e-6)

    def test_kl_standard_matches_mc(self, rng):
        mu, std = jnp.float32(0.7), jnp.float32(1.3)
        analytic = normal_kl_standard(mu, std)
        s = normal_sample(rng, mu, std, sample_shape=(200000,))
        mc = jnp.mean(normal_log_prob(s, mu, std) - (-0.5 * s * s - 0.5 * math.log(2 * math.pi)))
        np.testing.assert_allclose(analytic, mc, atol=0.02)


class TestBernoulli:
    def test_log_prob_binary_targets(self):
        p = jnp.array([0.2, 0.8])
        np.testing.assert_allclose(bernoulli_log_prob(jnp.array([1.0, 0.0]), p),
                                   np.log([0.2, 0.2]), rtol=1e-6)

    def test_clamp_keeps_finite_at_extremes(self):
        p = clamp_probs(jnp.array([0.0, 1.0]))
        lp = bernoulli_log_prob(jnp.array([1.0, 0.0]), p)
        assert np.all(np.isfinite(np.asarray(lp)))


class TestLogsumexp:
    def test_matches_naive_small(self):
        x = jnp.asarray(np.random.RandomState(1).randn(50, 4).astype(np.float32))
        np.testing.assert_allclose(logsumexp(x, 0), np.log(np.sum(np.exp(np.asarray(x, np.float64)), 0)),
                                   rtol=1e-5)

    def test_stable_at_extreme_values(self):
        x = jnp.array([[1000.0, -1000.0], [999.0, -999.0]])
        out = logmeanexp(x, axis=0)
        expected0 = 1000.0 + math.log((1 + math.exp(-1.0)) / 2)
        expected1 = -999.0 + math.log((1 + math.exp(-1.0)) / 2)
        np.testing.assert_allclose(out, [expected0, expected1], rtol=1e-6)

    def test_all_neg_inf_column(self):
        x = jnp.full((4, 2), -jnp.inf)
        assert np.all(np.asarray(logsumexp(x, 0)) == -np.inf)

    def test_gradient_is_softmax(self):
        x = jnp.asarray(np.random.RandomState(2).randn(6).astype(np.float32))
        g = jax.grad(lambda v: logsumexp(v, 0))(x)
        np.testing.assert_allclose(g, jax.nn.softmax(x), rtol=1e-5)


class TestOnlineLogsumexp:
    def test_chunked_equals_full(self):
        x = np.random.RandomState(3).randn(64, 5).astype(np.float32) * 10
        state = online_logsumexp_init((5,))
        for i in range(0, 64, 16):
            state = online_logsumexp_update(state, jnp.asarray(x[i:i + 16]), axis=0)
        got = online_logsumexp_finalize(state, mean=True)
        np.testing.assert_allclose(got, logmeanexp(jnp.asarray(x), 0), rtol=1e-5)

    def test_merge_associative(self):
        x = np.random.RandomState(4).randn(32, 3).astype(np.float32)
        a = online_logsumexp_update(online_logsumexp_init((3,)), jnp.asarray(x[:16]))
        b = online_logsumexp_update(online_logsumexp_init((3,)), jnp.asarray(x[16:]))
        merged = online_logsumexp_finalize(online_logsumexp_merge(a, b), mean=True)
        np.testing.assert_allclose(merged, logmeanexp(jnp.asarray(x), 0), rtol=1e-5)

    def test_streaming_fn(self):
        x = np.random.RandomState(5).randn(40, 6).astype(np.float32)
        xj = jnp.asarray(x)
        got = streaming_logmeanexp(lambda i: jax.lax.dynamic_slice_in_dim(xj, i * 8, 8, 0),
                                   k=40, chunk=8, shape=(6,))
        np.testing.assert_allclose(got, logmeanexp(xj, 0), rtol=1e-5)

    def test_streaming_rejects_bad_chunk(self):
        with pytest.raises(ValueError):
            streaming_logmeanexp(lambda i: jnp.zeros((7, 2)), k=40, chunk=7, shape=(2,))
