"""Distributed tests on the fake 8-device CPU mesh (SURVEY.md §4):
sharded-vs-single-device equivalence of losses and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import ModelConfig
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.parallel import (
    make_mesh,
    make_parallel_train_step,
    make_pjit_train_step,
    shard_batch,
)
from iwae_replication_project_tpu.parallel.dp import replicate
from iwae_replication_project_tpu.training import create_train_state, make_train_step

CFG = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                  n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12)
CFG2 = ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                   n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=12)


def make_batch(b=16, d=12):
    return (jax.random.uniform(jax.random.PRNGKey(42), (b, d)) > 0.5).astype(jnp.float32)


class TestMesh:
    def test_default_mesh_uses_all_devices(self, devices):
        mesh = make_mesh()
        assert mesh.shape == {"dp": 8, "sp": 1}

    def test_2d_mesh(self, devices):
        mesh = make_mesh(dp=4, sp=2)
        assert mesh.shape == {"dp": 4, "sp": 2}

    def test_bad_mesh_raises(self, devices):
        with pytest.raises(ValueError):
            make_mesh(dp=5, sp=3)


class TestDataParallel:
    @pytest.mark.parametrize("name", ["IWAE", "VAE", "MIWAE"])
    def test_dp_loss_matches_single_device(self, devices, rng, name):
        """Same params, same per-shard RNG structure -> bound within MC noise is
        not the point; instead check the *training dynamics*: loss decreases and
        params stay synchronized (replicated) after steps."""
        mesh = make_mesh(dp=8, sp=1)
        spec = ObjectiveSpec(name, k=8, k2=4)
        state = create_train_state(rng, CFG)
        state = replicate(mesh, state)
        step = make_parallel_train_step(spec, CFG, mesh, donate=False)
        batch = shard_batch(mesh, make_batch())
        losses = []
        for _ in range(20):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])

    def test_dp_grad_equals_single_device_when_rng_matched(self, devices, rng):
        """Bitwise-level check: with dp=1 (degenerate mesh) the sharded step must
        match the plain jitted step exactly."""
        mesh = make_mesh(dp=1, sp=1, devices=jax.devices()[:1])
        spec = ObjectiveSpec("IWAE", k=4)
        batch = make_batch(8)

        s0 = create_train_state(rng, CFG)
        single = make_train_step(spec, CFG, donate=False)
        s1, m1 = single(s0, batch)

        sp_state = replicate(mesh, create_train_state(rng, CFG))
        par = make_parallel_train_step(spec, CFG, mesh, donate=False)
        s2, m2 = par(sp_state, shard_batch(mesh, batch))

        # same objective value requires identical RNG; the parallel step folds in
        # axis indices (0 here) — so compare structurally + loss finiteness, and
        # param trees must agree in shape/dtype.
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a).shape,
                                                                np.asarray(b).shape),
                     s1.params, s2.params)
        assert np.isfinite(float(m1["loss"])) and np.isfinite(float(m2["loss"]))

    def test_pjit_path_matches_explicit_manual_rng(self, devices, rng):
        """pjit auto-sharded step must produce the same numbers as the plain
        single-device step (it is the same trace, just partitioned)."""
        mesh = make_mesh(dp=8, sp=1)
        spec = ObjectiveSpec("IWAE", k=4)
        batch = make_batch(16)

        s0 = create_train_state(rng, CFG)
        single = make_train_step(spec, CFG, donate=False)
        s1, m1 = single(s0, batch)

        step, place_state, place_batch = make_pjit_train_step(spec, CFG, mesh, donate=False)
        s2, m2 = step(place_state(create_train_state(rng, CFG)), place_batch(batch))

        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                             rtol=1e-4, atol=1e-6),
                     s1.params, s2.params)


class TestSampleParallel:
    def test_sp_bound_matches_global_logmeanexp(self, devices, rng):
        """The distributed logmeanexp over a sharded k axis must equal the
        single-device reduction of the gathered weights."""
        from jax import shard_map
        from jax.sharding import PartitionSpec as P
        from iwae_replication_project_tpu.parallel.dp import distributed_logmeanexp
        from iwae_replication_project_tpu.ops.logsumexp import logmeanexp

        mesh = make_mesh(dp=1, sp=8)
        log_w = jnp.asarray(np.random.RandomState(0).randn(64, 5).astype(np.float32) * 5)

        f = shard_map(lambda lw: distributed_logmeanexp(lw, "sp", 64),
                      mesh=mesh, in_specs=P("sp"), out_specs=P(), check_vma=False)
        got = f(log_w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(logmeanexp(log_w, 0)),
                                   rtol=1e-5)

    def test_sp_train_step_runs_and_descends(self, devices, rng):
        mesh = make_mesh(dp=2, sp=4)
        spec = ObjectiveSpec("IWAE", k=8)
        state = replicate(mesh, create_train_state(rng, CFG2))
        step = make_parallel_train_step(spec, CFG2, mesh, donate=False)
        batch = shard_batch(mesh, make_batch(8))
        losses = []
        for _ in range(20):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])

    @pytest.mark.parametrize("name", ["VAE", "CIWAE", "L_power_p", "MIWAE"])
    def test_sp_other_objectives_run(self, devices, rng, name):
        mesh = make_mesh(dp=1, sp=8)
        spec = ObjectiveSpec(name, k=16, k2=8, p=2.0, beta=0.3)
        state = replicate(mesh, create_train_state(rng, CFG))
        step = make_parallel_train_step(spec, CFG, mesh, donate=False)
        batch = shard_batch(mesh, make_batch(4))
        _, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_sp_unsupported_objective_raises(self, devices, rng):
        mesh = make_mesh(dp=1, sp=8)
        with pytest.raises(ValueError):
            make_parallel_train_step(ObjectiveSpec("L_median", k=16), CFG, mesh)

    def test_sp_must_divide_k(self, devices, rng):
        mesh = make_mesh(dp=1, sp=8)
        with pytest.raises(ValueError):
            make_parallel_train_step(ObjectiveSpec("IWAE", k=12), CFG, mesh)
