"""Distributed tests on the fake 8-device CPU mesh (SURVEY.md §4):
sharded-vs-single-device equivalence of losses and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import ModelConfig
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.parallel import (
    make_mesh,
    make_parallel_train_step,
    make_pjit_train_step,
    shard_batch,
)
from iwae_replication_project_tpu.parallel.dp import replicate
from iwae_replication_project_tpu.training import create_train_state, make_train_step

CFG = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                  n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12)
CFG2 = ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                   n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=12)


def make_batch(b=16, d=12):
    return (jax.random.uniform(jax.random.PRNGKey(42), (b, d)) > 0.5).astype(jnp.float32)


class TestMesh:
    def test_default_mesh_uses_all_devices(self, devices):
        mesh = make_mesh()
        assert mesh.shape == {"dp": 8, "sp": 1}

    def test_2d_mesh(self, devices):
        mesh = make_mesh(dp=4, sp=2)
        assert mesh.shape == {"dp": 4, "sp": 2}

    def test_bad_mesh_raises(self, devices):
        with pytest.raises(ValueError):
            make_mesh(dp=5, sp=3)


class TestDataParallel:
    @pytest.mark.parametrize("name", ["IWAE", "VAE", "MIWAE"])
    def test_dp_loss_matches_single_device(self, devices, rng, name):
        """Same params, same per-shard RNG structure -> bound within MC noise is
        not the point; instead check the *training dynamics*: loss decreases and
        params stay synchronized (replicated) after steps."""
        mesh = make_mesh(dp=8, sp=1)
        spec = ObjectiveSpec(name, k=8, k2=4)
        state = create_train_state(rng, CFG)
        state = replicate(mesh, state)
        step = make_parallel_train_step(spec, CFG, mesh, donate=False)
        batch = shard_batch(mesh, make_batch())
        losses = []
        for _ in range(20):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])

    @staticmethod
    def _reference_value_and_grad(spec, cfg, mesh, params, key, batch):
        """Single-device re-derivation of the sharded computation: fold the
        same (dp, sp) indices into the same key, gather each dp shard's k
        shards, reduce with the plain estimators, average over dp shards."""
        from iwae_replication_project_tpu.models import iwae as model
        from iwae_replication_project_tpu.objectives import (
            bound_from_log_weights,
            estimators as est,
        )

        n_dp, n_sp = mesh.shape["dp"], mesh.shape["sp"]
        b_local = batch.shape[0] // n_dp
        k_local = spec.k // n_sp

        def fold(i_dp, i_sp):
            return jax.random.fold_in(jax.random.fold_in(key, i_dp), i_sp)

        if spec.name in ("DReG", "STL", "PIWAE"):
            # composite forward over the sp key shards, then the estimators'
            # cotangent math (objectives/gradients.py) on the full [k, B]
            stop_q = spec.name in ("DReG", "STL")
            bounds, grad_trees = [], []
            for i_dp in range(n_dp):
                xs = batch[i_dp * b_local:(i_dp + 1) * b_local]
                B = xs.shape[0]

                def log_w_fn(p, xs=xs, i_dp=i_dp):
                    return jnp.concatenate([
                        model.log_weights(p, cfg, fold(i_dp, i_sp), xs,
                                          k_local, stop_q_score=stop_q)
                        for i_sp in range(n_sp)], axis=0)

                log_w, vjp = jax.vjp(log_w_fn, params)
                w_tilde = jax.lax.stop_gradient(jax.nn.softmax(log_w, axis=0))
                bounds.append(est.iwae_bound(log_w))
                if spec.name == "STL":
                    (g,) = vjp(w_tilde / B)
                elif spec.name == "DReG":
                    (ge,) = vjp(jnp.square(w_tilde) / B)
                    (gd,) = vjp(w_tilde / B)
                    g = dict(gd)
                    g["enc"] = ge["enc"]
                else:  # PIWAE
                    k2 = spec.k2
                    grouped = jax.lax.stop_gradient(log_w).reshape(
                        k2, spec.k // k2, B)
                    ct_enc = (jax.nn.softmax(grouped, axis=1)
                              .reshape(spec.k, B) / (k2 * B))
                    (gd,) = vjp(w_tilde / B)
                    (ge,) = vjp(ct_enc)
                    g = dict(gd)
                    g["enc"] = ge["enc"]
                grad_trees.append(g)
            bound = jnp.mean(jnp.asarray(bounds))
            grads = jax.tree.map(lambda *gs: jnp.mean(jnp.stack(gs), axis=0),
                                 *grad_trees)
            return bound, grads

        def loss(p):
            bounds = []
            for i_dp in range(n_dp):
                xs = batch[i_dp * b_local:(i_dp + 1) * b_local]
                lws, lpx = [], []
                for i_sp in range(n_sp):
                    lw, aux = model.log_weights_and_aux(p, cfg, fold(i_dp, i_sp),
                                                        xs, k_local)
                    lws.append(lw)
                    lpx.append(aux["log_px_given_h"])
                lw = jnp.concatenate(lws, axis=0)
                aux_c = {"log_px_given_h": jnp.concatenate(lpx, axis=0)}
                bounds.append(bound_from_log_weights(spec, lw, aux_c))
            return jnp.mean(jnp.asarray(bounds))

        return jax.value_and_grad(loss)(params)

    @pytest.mark.parametrize("dp,sp", [(8, 1), (4, 2), (2, 4)])
    @pytest.mark.parametrize("name", ["IWAE", "VAE"])
    @pytest.mark.slow
    def test_sharded_value_and_grad_matches_single_device(self, devices, rng,
                                                          dp, sp, name):
        """The load-bearing equivalence (SURVEY §4): loss AND per-leaf grads of
        the shard_map composition must match a matched-RNG single-device
        reference to float32 tolerance — a bug in the psum/pmean composition
        fails here."""
        from iwae_replication_project_tpu.parallel import make_parallel_value_and_grad

        mesh = make_mesh(dp=dp, sp=sp)
        spec = ObjectiveSpec(name, k=8)
        params = create_train_state(rng, CFG2).params
        key = jax.random.PRNGKey(7)
        batch = make_batch(16)

        vg = make_parallel_value_and_grad(spec, CFG2, mesh)
        bound_m, grads_m = vg(replicate(mesh, params), key, shard_batch(mesh, batch))
        bound_r, grads_r = self._reference_value_and_grad(spec, CFG2, mesh,
                                                          params, key, batch)

        np.testing.assert_allclose(float(bound_m), float(bound_r),
                                   rtol=2e-5, atol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6),
            grads_m, grads_r)

    @pytest.mark.parametrize("dp,sp", [(8, 1), (4, 2), (2, 4)])
    @pytest.mark.parametrize("name", ["DReG", "STL", "PIWAE"])
    @pytest.mark.slow
    def test_gradient_estimators_match_single_device(self, devices, rng,
                                                     dp, sp, name):
        """The modified-gradient estimators under dp AND sp sharding: the
        globally-normalized softmax cotangents (psum of per-shard denominators)
        must reproduce the single-device cotangent math exactly."""
        from iwae_replication_project_tpu.parallel import make_parallel_value_and_grad

        mesh = make_mesh(dp=dp, sp=sp)
        spec = ObjectiveSpec(name, k=8, k2=4)
        params = create_train_state(rng, CFG2).params
        key = jax.random.PRNGKey(3)
        batch = make_batch(16)

        vg = make_parallel_value_and_grad(spec, CFG2, mesh)
        bound_m, grads_m = vg(replicate(mesh, params), key, shard_batch(mesh, batch))
        bound_r, grads_r = self._reference_value_and_grad(spec, CFG2, mesh,
                                                          params, key, batch)

        np.testing.assert_allclose(float(bound_m), float(bound_r),
                                   rtol=2e-5, atol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6),
            grads_m, grads_r)

    @pytest.mark.parametrize("name,kw", [
        ("L_median", {}),
        ("CIWAE", {"beta": 0.3}),
        ("L_power_p", {"p": 2.0}),
        ("MIWAE", {"k2": 4}),
        ("L_alpha", {"alpha": 0.25}),
    ])
    def test_sp_objectives_match_single_device(self, devices, rng, name, kw):
        """Every remaining objective under (dp=4, sp=2): sharded loss+grads ==
        matched-RNG single-device reference (L_median exercises the all_gather
        path; L_alpha the aux-coupled recon term)."""
        from iwae_replication_project_tpu.parallel import make_parallel_value_and_grad

        mesh = make_mesh(dp=4, sp=2)
        spec = ObjectiveSpec(name, k=8, **kw)
        params = create_train_state(rng, CFG2).params
        key = jax.random.PRNGKey(17)
        batch = make_batch(16)

        vg = make_parallel_value_and_grad(spec, CFG2, mesh)
        bound_m, grads_m = vg(replicate(mesh, params), key, shard_batch(mesh, batch))
        bound_r, grads_r = self._reference_value_and_grad(spec, CFG2, mesh,
                                                          params, key, batch)

        np.testing.assert_allclose(float(bound_m), float(bound_r),
                                   rtol=2e-5, atol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6),
            grads_m, grads_r)

    @pytest.mark.slow
    def test_parallel_train_step_params_match_manual_update(self, devices, rng):
        """One full mesh train step == reference grads + the same optax update
        applied on a single device (catches key-threading drift between the
        step and the standalone value_and_grad)."""
        import optax
        from iwae_replication_project_tpu.training import make_adam

        mesh = make_mesh(dp=4, sp=2)
        spec = ObjectiveSpec("IWAE", k=8)
        state0 = create_train_state(rng, CFG2)
        batch = make_batch(16)

        par = make_parallel_train_step(spec, CFG2, mesh, donate=False)
        s_mesh, _ = par(replicate(mesh, state0), shard_batch(mesh, batch))

        # replicate the step's key handling: split, then per-device folds
        _, subkey = jax.random.split(state0.key)
        _, grads_r = self._reference_value_and_grad(spec, CFG2, mesh,
                                                    state0.params, subkey, batch)
        opt = make_adam()
        neg = jax.tree.map(jnp.negative, grads_r)
        updates, _ = opt.update(neg, state0.opt_state, state0.params)
        params_r = optax.apply_updates(state0.params, updates)

        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-6),
            s_mesh.params, params_r)

    def test_pjit_path_matches_explicit_manual_rng(self, devices, rng):
        """pjit auto-sharded step must produce the same numbers as the plain
        single-device step (it is the same trace, just partitioned)."""
        mesh = make_mesh(dp=8, sp=1)
        spec = ObjectiveSpec("IWAE", k=4)
        batch = make_batch(16)

        s0 = create_train_state(rng, CFG)
        single = make_train_step(spec, CFG, donate=False)
        s1, m1 = single(s0, batch)

        step, place_state, place_batch = make_pjit_train_step(spec, CFG, mesh, donate=False)
        s2, m2 = step(place_state(create_train_state(rng, CFG)), place_batch(batch))

        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
        jax.tree.map(lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                                             rtol=1e-4, atol=1e-6),
                     s1.params, s2.params)


class TestParallelEpoch:
    @pytest.mark.slow
    def test_mesh_multi_epoch_matches_repeated_single(self, devices, rng):
        """epochs_per_call under the mesh == repeated single-epoch dispatches
        (same key threading), with concatenated per-batch losses."""
        from iwae_replication_project_tpu.parallel import make_parallel_epoch_fn

        mesh = make_mesh(dp=4, sp=2)
        spec = ObjectiveSpec("IWAE", k=8)
        state0 = create_train_state(rng, CFG2)
        x_train = make_batch(32)

        single = make_parallel_epoch_fn(spec, CFG2, mesh, n_train=32,
                                        batch_size=16, donate=False)
        multi = make_parallel_epoch_fn(spec, CFG2, mesh, n_train=32,
                                       batch_size=16, donate=False,
                                       epochs_per_call=2)
        s1 = replicate(mesh, state0)
        ls = []
        for _ in range(2):
            s1, losses = single(s1, replicate(mesh, x_train))
            ls.append(np.asarray(losses))
        s2, losses2 = multi(replicate(mesh, state0), replicate(mesh, x_train))
        assert losses2.shape == (4,)
        np.testing.assert_allclose(np.asarray(losses2), np.concatenate(ls),
                                   rtol=1e-6)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            s1.params, s2.params)

    @pytest.mark.slow
    def test_mesh_epoch_matches_manual_steps(self, devices, rng):
        """The whole-epoch scan under the mesh == manual per-batch reference
        (matched RNG, same Adam updates) after a 2-batch epoch."""
        import optax
        from iwae_replication_project_tpu.parallel import make_parallel_epoch_fn
        from iwae_replication_project_tpu.training import make_adam

        mesh = make_mesh(dp=4, sp=2)
        spec = ObjectiveSpec("IWAE", k=8)
        state0 = create_train_state(rng, CFG2)
        x_train = make_batch(32)

        epoch = make_parallel_epoch_fn(spec, CFG2, mesh, n_train=32,
                                       batch_size=16, shuffle=False,
                                       donate=False)
        s_mesh, losses = epoch(replicate(mesh, state0),
                               replicate(mesh, x_train))
        assert np.all(np.isfinite(np.asarray(losses))) and losses.shape == (2,)

        opt = make_adam()
        _, k_batch, _, _ = jax.random.split(state0.key, 4)
        params, opt_state = state0.params, state0.opt_state
        for i in range(2):
            xb = x_train[i * 16:(i + 1) * 16]
            bkey = jax.random.fold_in(k_batch, i)
            bound, grads = TestDataParallel._reference_value_and_grad(
                spec, CFG2, mesh, params, bkey, xb)
            np.testing.assert_allclose(float(losses[i]), -float(bound),
                                       rtol=2e-5, atol=1e-6)
            neg = jax.tree.map(jnp.negative, grads)
            updates, opt_state = opt.update(neg, opt_state, params)
            params = optax.apply_updates(params, updates)

        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=1e-6),
            s_mesh.params, params)

    def test_mesh_epoch_descends_with_stochastic_binarization(self, devices, rng):
        from iwae_replication_project_tpu.parallel import make_parallel_epoch_fn

        mesh = make_mesh(dp=2, sp=4)
        spec = ObjectiveSpec("IWAE", k=8)
        state = replicate(mesh, create_train_state(rng, CFG))
        x_train = jnp.clip(jax.random.uniform(jax.random.PRNGKey(5), (64, 12)),
                           0.05, 0.95)
        epoch = make_parallel_epoch_fn(spec, CFG, mesh, n_train=64,
                                       batch_size=16,
                                       stochastic_binarization=True,
                                       donate=False)
        x_dev = replicate(mesh, x_train)
        first = None
        for _ in range(10):
            state, losses = epoch(state, x_dev)
            if first is None:
                first = float(jnp.mean(losses))
        assert np.isfinite(float(jnp.mean(losses)))
        assert float(jnp.mean(losses)) < first


class TestSampleParallel:
    def test_sp_bound_matches_global_logmeanexp(self, devices, rng):
        """The distributed logmeanexp over a sharded k axis must equal the
        single-device reduction of the gathered weights."""
        from jax.sharding import PartitionSpec as P
        from iwae_replication_project_tpu.parallel.mesh import shard_map
        from iwae_replication_project_tpu.parallel.dp import distributed_logmeanexp
        from iwae_replication_project_tpu.ops.logsumexp import logmeanexp

        mesh = make_mesh(dp=1, sp=8)
        log_w = jnp.asarray(np.random.RandomState(0).randn(64, 5).astype(np.float32) * 5)

        f = shard_map(lambda lw: distributed_logmeanexp(lw, "sp", 64),
                      mesh=mesh, in_specs=P("sp"), out_specs=P(), check_vma=False)
        got = f(log_w)
        np.testing.assert_allclose(np.asarray(got), np.asarray(logmeanexp(log_w, 0)),
                                   rtol=1e-5)

    def test_sp_train_step_runs_and_descends(self, devices, rng):
        mesh = make_mesh(dp=2, sp=4)
        spec = ObjectiveSpec("IWAE", k=8)
        state = replicate(mesh, create_train_state(rng, CFG2))
        step = make_parallel_train_step(spec, CFG2, mesh, donate=False)
        batch = shard_batch(mesh, make_batch(8))
        losses = []
        for _ in range(20):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])

    @pytest.mark.parametrize("name", ["VAE", "CIWAE", "L_power_p", "MIWAE"])
    def test_sp_other_objectives_run(self, devices, rng, name):
        mesh = make_mesh(dp=1, sp=8)
        spec = ObjectiveSpec(name, k=16, k2=8, p=2.0, beta=0.3)
        state = replicate(mesh, create_train_state(rng, CFG))
        step = make_parallel_train_step(spec, CFG, mesh, donate=False)
        batch = shard_batch(mesh, make_batch(4))
        _, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    @pytest.mark.slow
    def test_sp_train_step_runs_all_estimators(self, devices, rng):
        """Every objective trains under sp>1 (SP_SHARDABLE has no exclusions)."""
        mesh = make_mesh(dp=2, sp=2)
        for name in ("L_median", "DReG", "STL", "PIWAE"):
            spec = ObjectiveSpec(name, k=8, k2=4)
            state = replicate(mesh, create_train_state(rng, CFG))
            step = make_parallel_train_step(spec, CFG, mesh, donate=False)
            _, metrics = step(state, shard_batch(mesh, make_batch(8)))
            assert np.isfinite(float(metrics["loss"])), name

    def test_sp_must_divide_k(self, devices, rng):
        mesh = make_mesh(dp=1, sp=8)
        with pytest.raises(ValueError):
            make_parallel_train_step(ObjectiveSpec("IWAE", k=12), CFG, mesh)
