"""Sharded-evaluation tests on the fake 8-device CPU mesh: the k-sharded
streaming NLL and metric bundle must match a matched-RNG single-device
reference exactly (same reduction, different layout)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.evaluation import metrics as ev
from iwae_replication_project_tpu.models import ModelConfig, iwae as model
from iwae_replication_project_tpu.ops.logsumexp import logmeanexp
from iwae_replication_project_tpu.parallel import make_mesh
from iwae_replication_project_tpu.parallel.eval import (
    make_parallel_batch_metrics,
    make_parallel_posterior_means,
    make_parallel_streaming_log_px,
    parallel_training_statistics,
)
from iwae_replication_project_tpu.training import create_train_state

CFG = ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                  n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=12)


def make_x(b=16, d=12):
    return (jax.random.uniform(jax.random.PRNGKey(9), (b, d)) > 0.5).astype(jnp.float32)


def _fold(key, i_dp, i_sp):
    return jax.random.fold_in(jax.random.fold_in(key, i_dp), i_sp)


@pytest.mark.slow
class TestShardedStreamingNLL:
    @pytest.mark.parametrize("dp,sp", [(4, 2), (2, 4), (1, 8)])
    def test_matches_matched_rng_reference(self, devices, rng, dp, sp):
        """The distributed online-logsumexp merge == plain logmeanexp over the
        gathered per-device chunks."""
        mesh = make_mesh(dp=dp, sp=sp)
        params = create_train_state(rng, CFG).params
        key = jax.random.PRNGKey(11)
        x = make_x(16)
        from iwae_replication_project_tpu.evaluation.metrics import (
            largest_divisor_leq)

        k = 16
        k_local = k // sp
        chunk = largest_divisor_leq(k_local, 4)  # the fn adapts identically
        fn = make_parallel_streaming_log_px(CFG, mesh, k=k, chunk=4)
        got = np.asarray(fn(params, key, x))

        b_local = x.shape[0] // dp
        want = []
        for i_dp in range(dp):
            xs = x[i_dp * b_local:(i_dp + 1) * b_local]
            blocks = []
            for i_sp in range(sp):
                dev_key = _fold(key, i_dp, i_sp)
                for ci in range(k_local // chunk):
                    blocks.append(model.log_weights(
                        params, CFG, jax.random.fold_in(dev_key, ci), xs, chunk))
            want.append(logmeanexp(jnp.concatenate(blocks, axis=0), axis=0))
        want = np.asarray(jnp.concatenate(want))
        np.testing.assert_allclose(got, want, rtol=2e-5, atol=1e-6)


class TestShardedBatchMetrics:
    def test_matches_matched_rng_reference(self, devices, rng):
        mesh = make_mesh(dp=4, sp=2)
        params = create_train_state(rng, CFG).params
        key = jax.random.PRNGKey(13)
        x = make_x(16)
        k = 8
        fn = make_parallel_batch_metrics(CFG, mesh, k)
        got = fn(params, key, x)

        b_local = x.shape[0] // 4
        vae_terms, iwae_terms, recon_terms = [], [], []
        for i_dp in range(4):
            xs = x[i_dp * b_local:(i_dp + 1) * b_local]
            lws, recons = [], []
            for i_sp in range(2):
                lw, aux = model.log_weights_and_aux(
                    params, CFG, _fold(key, i_dp, i_sp), xs, k // 2)
                lws.append(lw)
                recons.append(aux["log_px_given_h"])
            lw = jnp.concatenate(lws, axis=0)
            vae_terms.append(jnp.mean(lw))
            iwae_terms.append(jnp.mean(logmeanexp(lw, axis=0)))
            recon_terms.append(jnp.mean(jnp.concatenate(recons, axis=0)))
        np.testing.assert_allclose(float(got["VAE"]),
                                   float(jnp.mean(jnp.asarray(vae_terms))),
                                   rtol=2e-5)
        np.testing.assert_allclose(float(got["IWAE"]),
                                   float(jnp.mean(jnp.asarray(iwae_terms))),
                                   rtol=2e-5)
        np.testing.assert_allclose(float(got["E_q(h|x)[log(p(x|h))]"]),
                                   float(jnp.mean(jnp.asarray(recon_terms))),
                                   rtol=2e-5)


class TestShardedActivity:
    def test_posterior_means_close_to_single_device(self, devices, rng):
        """Different RNG partition -> statistical agreement of the MC means."""
        mesh = make_mesh(dp=4, sp=2)
        params = create_train_state(rng, CFG).params
        x = make_x(8)
        from iwae_replication_project_tpu.evaluation.activity import (
            posterior_mean_activity)

        fn = make_parallel_posterior_means(CFG, mesh, n_samples=512, chunk=8)
        means = fn(params, jax.random.PRNGKey(1), x)
        v_sharded = tuple(jnp.var(m, axis=0) for m in means)
        v_single, _ = posterior_mean_activity(
            params, CFG, jax.random.PRNGKey(2), x, n_samples=512, chunk=8)
        for a, b in zip(v_sharded, v_single):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=0.5, atol=0.05)


@pytest.mark.slow
class TestParallelStatistics:
    def test_full_suite_schema_and_consistency(self, devices, rng):
        """The sharded statistics driver returns the reference schema, with
        values statistically consistent with the single-device driver."""
        mesh = make_mesh(dp=4, sp=2)
        params = create_train_state(rng, CFG).params
        x_test = make_x(32)
        res, res2 = parallel_training_statistics(
            params, CFG, mesh, jax.random.PRNGKey(3), x_test, k=8,
            batch_size=16, nll_k=32, nll_chunk=8, activity_samples=64)
        for key in ("VAE", "IWAE", "NLL", "E_q(h|x)[log(p(x|h))]",
                    "D_kl(q(h|x),p(h))", "D_kl(q(h|x),p(h|x))",
                    "reconstruction_loss", "LL_pruned"):
            assert np.isfinite(res[key]), key
        assert len(res2["number_of_active_units"]) == CFG.n_stochastic
        # the eval-RNG version stamp is the PER-DEVICE chunk actually used:
        # nll_k=32 over sp=2 -> 16 per device, clamped chunk ask 8 -> 8
        assert res["nll_chunk"] == 8.0

        res_s, _ = ev.training_statistics(
            params, CFG, jax.random.PRNGKey(4), x_test, k=8,
            batch_size=16, nll_k=32, nll_chunk=8, activity_samples=64)
        # independent MC draws: agree within a loose corridor
        assert abs(res["NLL"] - res_s["NLL"]) < 5.0
        assert abs(res["VAE"] - res_s["VAE"]) < 5.0

    def test_ragged_test_set_is_trimmed(self, devices, rng):
        mesh = make_mesh(dp=4, sp=2)
        params = create_train_state(rng, CFG).params
        res, _ = parallel_training_statistics(
            params, CFG, mesh, jax.random.PRNGKey(5), make_x(18), k=8,
            batch_size=8, nll_k=16, nll_chunk=8, activity_samples=64,
            include_pruned_nll=False)
        assert np.isfinite(res["NLL"])

    def test_small_eval_batch_floors_to_dp(self, devices, rng):
        """eval batch_size < dp must floor to dp, not crash with an empty
        max() (ADVICE r2)."""
        mesh = make_mesh(dp=8, sp=1)
        params = create_train_state(rng, CFG).params
        res, _ = parallel_training_statistics(
            params, CFG, mesh, jax.random.PRNGKey(6), make_x(32), k=8,
            batch_size=4, nll_k=16, nll_chunk=8, activity_samples=64,
            include_pruned_nll=False)
        assert np.isfinite(res["NLL"])

    def test_fused_scalars_rejects_undivisible_k(self, devices):
        """The fused whole-dataset factory enforces the same sp-divisibility
        guards as its per-batch siblings (silent truncation would bias every
        scalar)."""
        from iwae_replication_project_tpu.parallel.eval import (
            make_parallel_dataset_scalars)
        mesh = make_mesh(dp=4, sp=2)
        with pytest.raises(ValueError, match="must divide"):
            make_parallel_dataset_scalars(CFG, mesh, k=7, nll_k=16,
                                          nll_chunk=8)
        with pytest.raises(ValueError, match="must divide"):
            make_parallel_dataset_scalars(CFG, mesh, k=8, nll_k=17,
                                          nll_chunk=8)
