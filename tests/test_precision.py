"""Low-precision serving policies (ISSUE 16).

Five layers, mirroring the PR's ownership chain:

* **parity gate** (telemetry/parity.py) — the statistical acceptance
  helper itself, on synthetic log-weight sets with KNOWN bias/variance:
  accepts inside every bound, rejects outside in BOTH directions, NaN can
  never pass, shape mismatch and zero tolerances are typed errors;
* **vocabulary** — an unknown precision string is a typed error at every
  boundary (dtypes validator, ExperimentConfig ctor + ``--serving-precision``
  CLI, ServingEngine ctor, zoo manifest, ``iwae-serve --precision``) and
  NEVER a silent fp32 fallback;
* **store hygiene** — (model, precision) variants of one model land under
  distinct ``model@precision`` store labels (no collision), the int8
  variant bills FEWER resident bytes than its fp32 twin (weight-only int8
  is actually smaller, not just relabeled), and eviction accounting stays
  exact with two precisions of one model resident;
* **engine** — an explicit fp32 policy is bitwise against the no-policy
  oracle; bf16/int8 answers stay inside the policy row tolerances; int8
  auto mode without a measured win serves the exact fp32 program and
  records WHY;
* **wire** — ``precision`` on a request is validated by the one shared
  validator and asserted against what the fleet holds (typed
  ``bad_request`` both ways, connection survives), and ``info()``
  declares each tenant's policy.
"""

import dataclasses
import json
import os
import socket
from concurrent.futures import Future

import jax
import numpy as np
import pytest

from iwae_replication_project_tpu.telemetry.parity import (
    BF16_TOLERANCES, DEFAULT_TOLERANCES, INT8_TOLERANCES, ParityTolerances,
    statistical_parity)
from iwae_replication_project_tpu.utils import compile_cache as cc
from iwae_replication_project_tpu.utils.dtypes import validate_precision


def _log_weights(k=8, b=64, seed=0):
    """Synthetic [k, B] log-weight matrix with known spread."""
    return np.random.RandomState(seed).normal(size=(k, b))


# ---------------------------------------------------------------------------
# the statistical acceptance helper itself
# ---------------------------------------------------------------------------

class TestParityGate:
    def test_identical_legs_accept_with_zero_deltas(self):
        lw = _log_weights()
        v = statistical_parity(lw, lw.copy(), BF16_TOLERANCES)
        assert v["accepted"] and not v["failures"]
        assert all(d == 0.0 for d in v["deltas"].values())

    def test_known_bias_inside_bounds_accepts_both_directions(self):
        """A uniform bias of b nats shifts every row estimate by exactly
        b, so the gate's behavior on it is analytically known."""
        lw = _log_weights()
        for sign in (+1.0, -1.0):
            v = statistical_parity(lw, lw + sign * 0.015, INT8_TOLERANCES)
            assert v["accepted"], (sign, v["failures"])
            assert v["deltas"]["batch_nll"] == pytest.approx(0.015)

    def test_known_bias_outside_bounds_rejects_both_directions(self):
        """A 'better' NLL (negative bias) is as much a violation as a
        worse one — the program is not computing the tenant's model."""
        lw = _log_weights()
        for sign in (+1.0, -1.0):
            v = statistical_parity(lw, lw + sign * 1.0, INT8_TOLERANCES)
            assert not v["accepted"], sign
            assert any("batch_nll" in f for f in v["failures"])

    def test_known_variance_inflation_rejected(self):
        """Inflating the per-row spread by f multiplies Var_k[log w] by
        f^2 — coverage drift the mean-level gates alone would miss."""
        lw = _log_weights()
        mean = lw.mean(axis=0, keepdims=True)
        v = statistical_parity(lw, mean + 2.0 * (lw - mean),
                               INT8_TOLERANCES)
        assert not v["accepted"]
        assert any("log_weight_var_rel" in f for f in v["failures"])
        assert v["deltas"]["log_weight_var"] == pytest.approx(
            3.0 * v["ref"]["log_weight_var"], rel=1e-6)

    def test_nan_leg_can_never_be_accepted(self):
        lw = _log_weights()
        bad = lw.copy()
        bad[0, 0] = np.nan
        v = statistical_parity(lw, bad, INT8_TOLERANCES)
        assert not v["accepted"] and v["failures"]

    def test_shape_mismatch_is_a_typed_error(self):
        with pytest.raises(ValueError, match="shapes differ"):
            statistical_parity(_log_weights(k=8), _log_weights(k=4),
                               BF16_TOLERANCES)

    def test_zero_or_negative_tolerance_is_a_typed_error(self):
        """A zero tolerance is a request for bitwise parity — serve fp32
        instead of building a gate that can only fail."""
        for bad in (0.0, -0.1):
            with pytest.raises(ValueError, match="must be > 0"):
                ParityTolerances(bad, 0.1, 0.1, 0.1)

    def test_defaults_cover_exactly_the_low_precision_policies(self):
        """fp32 has no statistical gate on purpose: its contract is
        bitwise identity, checked directly by the callers."""
        assert set(DEFAULT_TOLERANCES) == {"bf16", "int8"}

    def test_verdict_is_json_ready(self):
        lw = _log_weights()
        v = statistical_parity(lw, lw + 0.01, BF16_TOLERANCES)
        json.dumps(v)   # artifacts (bench, smoke) embed verdicts verbatim


# ---------------------------------------------------------------------------
# vocabulary: typed errors at every boundary, never a silent fp32
# ---------------------------------------------------------------------------

class TestPrecisionVocabulary:
    def test_validator_accepts_policies_and_returns_them(self):
        for p in ("fp32", "bf16", "int8"):
            assert validate_precision(p) == p

    def test_validator_rejects_unknowns_typed(self):
        for bad in ("fp16", "FP32", "", "int4", 8, None):
            with pytest.raises((ValueError, TypeError)):
                validate_precision(bad)

    def test_config_ctor_boundary(self):
        from iwae_replication_project_tpu.utils.config import (
            ExperimentConfig)

        cfg = ExperimentConfig(serving_precision="int8")
        assert cfg.serving_precision == "int8"
        with pytest.raises(ValueError, match="fp16"):
            ExperimentConfig(serving_precision="fp16")

    def test_config_cli_boundary(self):
        from iwae_replication_project_tpu.utils.config import (
            config_from_args)

        cfg = config_from_args(["--serving-precision", "bf16"])
        assert cfg.serving_precision == "bf16"
        with pytest.raises(ValueError, match="int4"):
            config_from_args(["--serving-precision", "int4"])

    def test_config_json_roundtrip_keeps_policy(self):
        from iwae_replication_project_tpu.utils.config import (
            ExperimentConfig)

        cfg = ExperimentConfig(serving_precision="bf16")
        back = ExperimentConfig.from_json(cfg.to_json())
        assert back.serving_precision == "bf16"

    def test_engine_ctor_boundary(self):
        with pytest.raises(ValueError, match="fp16"):
            _tiny_engine(precision="fp16")

    def test_zoo_manifest_boundary(self):
        from iwae_replication_project_tpu import zoo

        with pytest.raises(ValueError, match="fp16"):
            zoo.serving_engines(["northstar-iwae-2l-k50"],
                                precisions="fp16")
        with pytest.raises(ValueError, match="not in this manifest"):
            zoo.serving_engines(["northstar-iwae-2l-k50"],
                                precisions={"table1-vae-1l-k1": "bf16"})

    def test_serve_cli_boundary(self):
        from iwae_replication_project_tpu.serving.cli import (
            _parse_precision)

        assert _parse_precision(None) is None
        assert _parse_precision("bf16") == "bf16"
        assert _parse_precision("m1=bf16,m2=int8") == {"m1": "bf16",
                                                       "m2": "int8"}
        for bad in ("fp16", "m1=fp16", "m1=bf16,int8", "=bf16"):
            with pytest.raises(SystemExit, match="--precision"):
                _parse_precision(bad)


# ---------------------------------------------------------------------------
# store hygiene + billing: two precisions of one model, one store
# ---------------------------------------------------------------------------

def _tiny_engine(model=None, precision=None, **kw):
    from iwae_replication_project_tpu.models import iwae as m
    from iwae_replication_project_tpu.serving import ServingEngine

    D = 16
    cfg = m.ModelConfig(x_dim=D, n_hidden_enc=(8,), n_latent_enc=(4,),
                        n_hidden_dec=(8,), n_latent_dec=(D,))
    params = m.init_params(jax.random.PRNGKey(0), cfg)
    return ServingEngine(params=params, model_config=cfg, k=3, max_batch=4,
                         model=model, precision=precision, **kw)


def _serve_one(eng, seed=0):
    fut = eng.submit("score", [0.5] * 16, seed=seed)
    eng.flush()
    return float(fut.result())


class TestStorePrecisionHygiene:
    def _resident_pair(self):
        """fp32-policy and forced-int8 engines of the SAME model label,
        one program each, in the caller's isolated store."""
        e32 = _tiny_engine(model="m", precision="fp32")
        _serve_one(e32)
        saved = os.environ.get("IWAE_SERVING_INT8")
        os.environ["IWAE_SERVING_INT8"] = "force"
        try:
            e8 = _tiny_engine(model="m", precision="int8")
            _serve_one(e8)
        finally:
            if saved is None:
                os.environ.pop("IWAE_SERVING_INT8", None)
            else:
                os.environ["IWAE_SERVING_INT8"] = saved
        return e32, e8

    def test_precision_variants_never_collide(self):
        with cc.isolated_aot_registry(budget_bytes=None):
            self._resident_pair()
            store = cc.executable_store()
            per_model = store.stats()["per_model"]
            assert {"m@fp32", "m@int8"} <= set(per_model), \
                sorted(per_model)
            # distinct entries, and the precision rides the build key of
            # every quantized entry (no (model, precision) aliasing)
            models = [e["model"] for e in store.entries()]
            assert models.count("m@fp32") >= 1
            assert models.count("m@int8") >= 1
            int8_keys = [k for k in store.keys()
                         if k[0] == "m@int8"]
            assert int8_keys and all(
                "int8" in str(k[2]) for k in int8_keys), int8_keys

    def test_int8_entry_bills_less_than_fp32_twin(self):
        """Weight-only int8 must be cheaper under the store budget, not
        just relabeled: its params tree swaps fp32 decoder matrices for
        int8 weights + per-channel fp32 scales."""
        with cc.isolated_aot_registry(budget_bytes=None):
            self._resident_pair()
            per_model = cc.executable_store().stats()["per_model"]
            b32 = per_model["m@fp32"]["resident_bytes"]
            b8 = per_model["m@int8"]["resident_bytes"]
            assert b32 > 0 and b8 > 0
            assert b8 < b32, (b8, b32)

    def test_eviction_accounting_exact_with_two_precisions(self):
        with cc.isolated_aot_registry(budget_bytes=None):
            s0 = cc.cache_stats()
            self._resident_pair()
            store = cc.executable_store()
            stats = store.stats()
            # resident bytes reconcile bit-exactly across the three views
            assert stats["resident_bytes"] == \
                sum(e["bytes"] for e in store.entries()) == \
                sum(m["resident_bytes"]
                    for m in stats["per_model"].values())
            # squeeze until something goes; accounting must stay exact
            # (per-model counters are process-cumulative, so compare
            # deltas, not absolutes)
            pre_ev = {m: v["evictions"]
                      for m, v in stats["per_model"].items()}
            store.set_budget(stats["resident_bytes"] - 1)
            after = store.stats()
            assert after["resident_bytes"] <= stats["resident_bytes"] - 1
            assert after["resident_bytes"] == \
                sum(e["bytes"] for e in store.entries()) == \
                sum(m["resident_bytes"]
                    for m in after["per_model"].values())
            evicted = {m: v["evictions"] - pre_ev.get(m, 0)
                       for m, v in after["per_model"].items()
                       if v["evictions"] != pre_ev.get(m, 0)}
            assert sum(evicted.values()) == \
                cc.stats_delta(s0)["store_evictions"] > 0
            # and the churn stayed inside this model's precision variants
            assert set(evicted) <= {"m@fp32", "m@int8"}, evicted


# ---------------------------------------------------------------------------
# engine: fp32 bitwise, bf16/int8 bounded, auto admission honest
# ---------------------------------------------------------------------------

class TestEnginePrecision:
    N = 4

    def _rows(self):
        rng = np.random.RandomState(1)
        return (rng.rand(self.N, 16) > 0.5).astype(np.float32)

    def _serve(self, eng):
        rows = self._rows()
        futs = [eng.submit("score", rows[i], seed=i)
                for i in range(self.N)]
        eng.flush()
        return [float(f.result()) for f in futs]

    def _oracle(self):
        with cc.isolated_aot_registry():
            return self._serve(_tiny_engine())

    def test_fp32_policy_is_bitwise(self):
        ref = self._oracle()
        with cc.isolated_aot_registry():
            assert self._serve(_tiny_engine(precision="fp32")) == ref

    def test_bf16_and_forced_int8_within_row_tolerance(self):
        ref = self._oracle()
        scale = max(1.0, abs(float(np.mean(ref))))
        with cc.isolated_aot_registry():
            got = self._serve(_tiny_engine(precision="bf16"))
        worst = max(abs(a - b) for a, b in zip(got, ref))
        assert worst <= BF16_TOLERANCES.max_row_rel_delta * scale, worst

        saved = os.environ.get("IWAE_SERVING_INT8")
        os.environ["IWAE_SERVING_INT8"] = "force"
        try:
            with cc.isolated_aot_registry():
                e8 = _tiny_engine(precision="int8")
                got8 = self._serve(e8)
                snap = e8.metrics.snapshot()
        finally:
            if saved is None:
                os.environ.pop("IWAE_SERVING_INT8", None)
            else:
                os.environ["IWAE_SERVING_INT8"] = saved
        worst8 = max(abs(a - b) for a, b in zip(got8, ref))
        assert worst8 <= INT8_TOLERANCES.max_row_rel_delta * scale, worst8
        # the quantized path really served, stamped with its precision
        int8_recs = [rec for rec in snap["kernel"].values()
                     if rec.get("path") == "int8"]
        assert int8_recs and all(
            rec["precision"] == "int8" for rec in int8_recs)

    def test_auto_without_measured_win_serves_exact_fp32(self):
        """CPU CI leg of admission honesty: no autotuner win -> the
        EXACT fp32 program serves and the engine records why."""
        ref = self._oracle()
        with cc.isolated_aot_registry():
            e = _tiny_engine(precision="int8")
            got = self._serve(e)
            reasons = dict(e.int8_admission)
            admitted = any(rec.get("path") == "int8" for rec in
                           e.metrics.snapshot()["kernel"].values())
        assert reasons, "auto int8 recorded no admission decisions"
        if not admitted:        # the only possibility off-TPU
            assert got == ref
            assert any("measured win" in r for r in reasons.values())

    def test_unknown_admission_env_is_a_typed_error(self):
        from iwae_replication_project_tpu.ops.hot_loop import (
            serving_int8_admit)

        saved = os.environ.get("IWAE_SERVING_INT8")
        os.environ["IWAE_SERVING_INT8"] = "sometimes"
        try:
            with pytest.raises(ValueError, match="IWAE_SERVING_INT8"):
                serving_int8_admit(3, 4, 8, 8, 16, on_tpu=False)
        finally:
            if saved is None:
                os.environ.pop("IWAE_SERVING_INT8", None)
            else:
                os.environ["IWAE_SERVING_INT8"] = saved


# ---------------------------------------------------------------------------
# wire: precision is validated + asserted per request, declared in info
# ---------------------------------------------------------------------------

class PrecisionFakeEngine:
    """Minimal engine surface with model + precision labels (no device):
    the wire contract under test is validation/declaration, not math."""

    def __init__(self, model, precision=None, dims=4):
        self.model = model
        self.models = frozenset({model})
        self.row_dims = {"score": dims}
        self.k = 5
        self.precision = precision

    def submit(self, op, row, k=None, *, seed=None, model=None):
        f = Future()
        f.set_result(float(sum(row)))
        return f

    def start(self):
        pass

    def stop(self, timeout_s=None):
        pass

    def warmup(self, ops=(), ks=None):
        return {"programs": 0.0}


def _raw_request(port, req):
    """One request over a raw socket (TierClient has no precision kwarg:
    the field under test is the wire schema itself)."""
    from iwae_replication_project_tpu.serving.frontend import protocol

    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall(protocol.encode_line(req))
        buf = b""
        while b"\n" not in buf:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.split(b"\n", 1)[0].decode())


class TestWirePrecision:
    def _tier(self):
        from iwae_replication_project_tpu.serving.frontend import (
            ServingTier)

        tier = ServingTier([PrecisionFakeEngine("m-a"),
                            PrecisionFakeEngine("m-b", precision="bf16")],
                           port=0)
        tier.start()
        return tier

    def test_precisions_for_reports_fleet_policies(self):
        tier = self._tier()
        try:
            assert tier.precisions_for("m-a") == {"fp32"}
            assert tier.precisions_for("m-b") == {"bf16"}
        finally:
            tier.stop(timeout_s=10)

    def test_unknown_precision_is_bad_request_connection_survives(self):
        tier = self._tier()
        try:
            resp = _raw_request(tier.port, {
                "id": 1, "op": "score", "x": [1.0] * 4, "model": "m-a",
                "precision": "fp16"})
            assert resp["ok"] is False
            assert resp["error"] == "bad_request"
            assert "fp16" in resp["message"]
            # vocabulary-valid but not held here: equally typed, with the
            # held set in the message — never a silent serve
            resp = _raw_request(tier.port, {
                "id": 2, "op": "score", "x": [1.0] * 4, "model": "m-a",
                "precision": "int8"})
            assert resp["ok"] is False
            assert resp["error"] == "bad_request"
            assert "not served at precision" in resp["message"]
            assert "fp32" in resp["message"]
        finally:
            tier.stop(timeout_s=10)

    def test_matching_precision_assertion_serves(self):
        tier = self._tier()
        try:
            for model, precision in (("m-a", "fp32"), ("m-b", "bf16")):
                resp = _raw_request(tier.port, {
                    "id": 1, "op": "score", "x": [1.0] * 4,
                    "model": model, "precision": precision})
                assert resp["ok"] is True, resp
        finally:
            tier.stop(timeout_s=10)

    def test_info_declares_per_model_precision(self):
        tier = self._tier()
        try:
            models = tier.info()["models"]
            assert models["m-a"]["precision"] == "fp32"
            assert models["m-b"]["precision"] == "bf16"
        finally:
            tier.stop(timeout_s=10)
