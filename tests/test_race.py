"""Tests for the iwae-race package (analysis/race/): the lockset +
happens-before detector, the deterministic schedule fuzzers, the
instrumented-sync layer's install/uninstall contract, the static
thread-escape and future/span/pin leak passes, and the CLI.

Per ISSUE 17: every HB-edge mechanism gets a fixture PAIR (a racy variant
the detector must catch with a reproducing seed, and a synchronized twin
that must stay clean); same-seed cooperative runs serialize to
byte-identical reports; and instrumentation-off is the byte-identical
pre-instrumentation code path — pinned here by comparing a real
``ServingEngine``'s bitwise outputs with the layer installed, uninstalled,
and never-installed.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from iwae_replication_project_tpu.analysis import (
    LintConfig,
    lint_paths,
    load_config,
)
from iwae_replication_project_tpu.analysis.race import (
    CooperativeScheduler,
    Instrumentation,
    PerturbFuzzer,
    RaceDetector,
    SchedulerDeadlock,
    VectorClock,
)
from iwae_replication_project_tpu.analysis.race import cli as race_cli
from iwae_replication_project_tpu.analysis.race import escape
from iwae_replication_project_tpu.analysis.race.escape import classify_class

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: the cooperative fixtures schedule each racy variant under these seeds;
#: the pairs' conflicting accesses are adjacent in program order, so a
#: handful of seeded interleavings reliably includes an exposing one
SEEDS = (0, 1, 2, 3, 4)


# ---------------------------------------------------------------------------
# vector clocks
# ---------------------------------------------------------------------------

class TestVectorClock:
    def test_tick_and_dominates(self):
        vc = VectorClock()
        assert vc.time_of(0) == 0
        vc.tick(0)
        vc.tick(0)
        assert vc.time_of(0) == 2
        assert vc.dominates(0, 2)
        assert not vc.dominates(0, 3)
        assert vc.dominates(1, 0)       # time 0 is vacuously seen

    def test_join_is_componentwise_max(self):
        a, b = VectorClock({0: 3, 1: 1}), VectorClock({1: 5, 2: 2})
        a.join(b)
        assert a.c == {0: 3, 1: 5, 2: 2}
        assert b.c == {1: 5, 2: 2}      # join mutates only the receiver

    def test_copy_is_independent(self):
        a = VectorClock({0: 1})
        b = a.copy()
        b.tick(0)
        assert a.time_of(0) == 1 and b.time_of(0) == 2


# ---------------------------------------------------------------------------
# detector core: each HB edge, exercised directly (two OS threads whose
# REAL ordering is enforced by raw untraced events, so the only HB the
# detector can see is what the fixture explicitly records)
# ---------------------------------------------------------------------------

def _sequenced_pair(det, first, second):
    """Run `first` then `second` on two distinct live OS threads. Raw
    events order the bodies without telling the detector anything; both
    threads stay alive until both ran (no ident reuse aliasing tids)."""
    e1, e2 = threading.Event(), threading.Event()
    errs = []

    def a():
        try:
            det.register_thread("A")
            first()
        except Exception as e:          # pragma: no cover - harness bug
            errs.append(e)
        finally:
            e1.set()
        e2.wait(10)

    def b():
        e1.wait(10)
        try:
            det.register_thread("B")
            second()
        except Exception as e:          # pragma: no cover - harness bug
            errs.append(e)
        finally:
            e2.set()

    ta = threading.Thread(target=a)
    tb = threading.Thread(target=b)
    ta.start()
    tb.start()
    ta.join(10)
    tb.join(10)
    assert not errs, errs
    assert not ta.is_alive() and not tb.is_alive()


class TestDetectorEdges:
    def test_unordered_unlocked_writes_race(self):
        det = RaceDetector(capture_stacks=False)
        _sequenced_pair(det,
                        lambda: det.access("v", write=True),
                        lambda: det.access("v", write=True))
        assert det.report()["total"] == 1

    def test_write_read_races_but_read_read_does_not(self):
        det = RaceDetector(capture_stacks=False)
        _sequenced_pair(det,
                        lambda: det.access("v", write=True),
                        lambda: det.access("v", write=False))
        assert det.report()["total"] == 1
        det2 = RaceDetector(capture_stacks=False)
        _sequenced_pair(det2,
                        lambda: det2.access("v", write=False),
                        lambda: det2.access("v", write=False))
        assert det2.report()["total"] == 0

    def test_common_lockset_suppresses(self):
        det = RaceDetector(capture_stacks=False)

        def locked_write():
            det.lock_acquired("L")
            det.access("v", write=True)
            det.lock_released("L")

        _sequenced_pair(det, locked_write, locked_write)
        assert det.report()["total"] == 0

    def test_distinct_locks_do_not_suppress(self):
        # disjoint locksets AND no shared sync clock: still a race — the
        # hybrid falls back to neither ingredient
        det = RaceDetector(capture_stacks=False)

        def under(name):
            det.lock_acquired(name)
            det.access("v", write=True)
            det.lock_released(name)

        _sequenced_pair(det, lambda: under("L1"), lambda: under("L2"))
        assert det.report()["total"] == 1

    def test_future_completion_edge(self):
        det = RaceDetector(capture_stacks=False)

        def produce():
            det.access("v", write=True)
            det.future_completed(7)

        def consume():
            det.future_observed(7)
            det.access("v", write=True)

        _sequenced_pair(det, produce, consume)
        assert det.report()["total"] == 0

    def test_callback_registration_edge(self):
        # add_done_callback: registration publishes the registrant's
        # history to the invocation (modeled as a completion of the same
        # clock) — the edge that orders closure state handed to callbacks
        det = RaceDetector(capture_stacks=False)

        def register():
            det.access("v", write=True)
            det.future_registered(7)

        def invoke():
            det.future_observed(7)
            det.access("v", write=True)

        _sequenced_pair(det, register, invoke)
        assert det.report()["total"] == 0

    def test_queue_fifo_edge(self):
        det = RaceDetector(capture_stacks=False)

        def put():
            det.access("v", write=True)
            det.queue_put(1)

        def get():
            det.queue_got(1)
            det.access("v", write=True)

        _sequenced_pair(det, put, get)
        assert det.report()["total"] == 0

    def test_event_set_edge(self):
        det = RaceDetector(capture_stacks=False)

        def setter():
            det.access("v", write=True)
            det.event_set(3)

        def waiter():
            det.event_observed(3)
            det.access("v", write=True)

        _sequenced_pair(det, setter, waiter)
        assert det.report()["total"] == 0

    def test_lock_release_acquire_edge(self):
        # TSan hb-mode: a critical section on L publishes everything its
        # thread did BEFORE it (the bare write included) to the next
        # acquirer of L — the serving stack's ownership-handoff idiom
        det = RaceDetector(capture_stacks=False)

        def handoff():
            det.access("v", write=True)         # bare, pre-section
            det.lock_acquired("L")
            det.lock_released("L")

        def successor():
            det.lock_acquired("L")
            det.lock_released("L")
            det.access("v", write=True)         # bare, post-section

        _sequenced_pair(det, handoff, successor)
        assert det.report()["total"] == 0

    def test_lock_edge_is_directional(self):
        # the same two critical sections do NOT order an access that
        # happens before the second thread's acquire — proof the clean
        # verdict above comes from the sync clock, not from the lockset
        det = RaceDetector(capture_stacks=False)

        def handoff():
            det.access("v", write=True)
            det.lock_acquired("L")
            det.lock_released("L")

        def too_early():
            det.access("v", write=True)         # before joining L's clock
            det.lock_acquired("L")
            det.lock_released("L")

        _sequenced_pair(det, handoff, too_early)
        assert det.report()["total"] == 1

    def test_report_is_deduped_per_program_point(self):
        det = RaceDetector(capture_stacks=False)

        def writes():
            for _ in range(5):
                det.access("v", write=True)

        _sequenced_pair(det, writes, writes)
        # many dynamic conflicts, one (var, stacks) program-point pair
        assert det.report()["total"] == 1


# ---------------------------------------------------------------------------
# cooperative fixtures: a racy/synchronized pair per mechanism, driven by
# the seeded single-baton scheduler (every catch carries its repro seed)
# ---------------------------------------------------------------------------

def _cooperative(seed):
    det = RaceDetector()
    sched = CooperativeScheduler(seed)
    ins = Instrumentation(detector=det, fuzz=sched)

    class Box:
        def __init__(self):
            self.v = 0

    box = ins.track(Box())
    return det, sched, ins, box


def _run_threads(sched, ins, *bodies):
    def driver():
        ts = [ins.thread(target=b, name=f"w{i}")
              for i, b in enumerate(bodies)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    sched.run(driver)


def _future_fixture(seed, ordered):
    det, sched, ins, box = _cooperative(seed)
    fut = ins.future()

    def producer():
        box.v = 1
        fut.set_result(1)

    def consumer():
        if ordered:
            fut.result()
        n = box.v                       # noqa: F841 - the traced read

    _run_threads(sched, ins, producer, consumer)
    return det.report()


def _queue_fixture(seed, ordered):
    det, sched, ins, box = _cooperative(seed)
    q = ins.make_queue()

    def producer():
        box.v = 1
        q.put("item")

    def consumer():
        if ordered:
            q.get()
        n = box.v                       # noqa: F841

    _run_threads(sched, ins, producer, consumer)
    return det.report()


def _event_fixture(seed, ordered):
    det, sched, ins, box = _cooperative(seed)
    evt = ins.event()

    def setter():
        box.v = 1
        evt.set()

    def waiter():
        if ordered:
            evt.wait()
        n = box.v                       # noqa: F841

    _run_threads(sched, ins, setter, waiter)
    return det.report()


def _join_fixture(seed, ordered):
    det, sched, ins, box = _cooperative(seed)

    def bump():
        box.v = box.v + 1

    def driver():
        t1 = ins.thread(target=bump, name="w1")
        t2 = ins.thread(target=bump, name="w2")
        if ordered:
            t1.start()
            t1.join()                   # join edge orders the pair
            t2.start()
            t2.join()
        else:
            t1.start()
            t2.start()
            t1.join()
            t2.join()

    sched.run(driver)
    return det.report()


def _callback_fixture(seed, ordered):
    det, sched, ins, box = _cooperative(seed)
    fut = ins.future()

    if ordered:
        # registrant writes, then registers a callback reading the same
        # state; a second thread completes the future — the registration
        # edge orders write -> callback regardless of completer thread
        def driver():
            box.v = 1
            fut.add_done_callback(lambda f: box.v)
            t = ins.thread(target=lambda: fut.set_result(1), name="comp")
            t.start()
            t.join()
    else:
        # two futures completed by two threads, both callbacks write the
        # same attr: the callbacks run on unordered completer threads
        fut2 = ins.future()

        def bump(f):
            f()
            box.v = box.v + 1

        fut.add_done_callback(lambda f: bump(lambda: None))
        fut2.add_done_callback(lambda f: bump(lambda: None))

        def driver():
            t1 = ins.thread(target=lambda: fut.set_result(1), name="c1")
            t2 = ins.thread(target=lambda: fut2.set_result(1), name="c2")
            t1.start()
            t2.start()
            t1.join()
            t2.join()

    sched.run(driver)
    return det.report()


_PAIRS = {
    "future": _future_fixture,
    "queue": _queue_fixture,
    "event": _event_fixture,
    "start_join": _join_fixture,
    "callback": _callback_fixture,
}


class TestCooperativePairs:
    @pytest.mark.parametrize("mechanism", sorted(_PAIRS))
    def test_racy_variant_is_caught_with_a_repro_seed(self, mechanism):
        fixture = _PAIRS[mechanism]
        caught = [s for s in SEEDS if fixture(s, ordered=False)["total"] > 0]
        assert caught, f"{mechanism}: no seed exposed the racy twin"
        # the report names its schedule: re-running the seed reproduces
        report = fixture(caught[0], ordered=False)
        assert report["seed"] == caught[0] and report["total"] > 0

    @pytest.mark.parametrize("mechanism", sorted(_PAIRS))
    def test_synchronized_twin_is_clean_under_every_seed(self, mechanism):
        fixture = _PAIRS[mechanism]
        for seed in SEEDS:
            report = fixture(seed, ordered=True)
            assert report["total"] == 0, \
                f"{mechanism}: false positive under seed {seed}: " \
                f"{report['races']}"

    @pytest.mark.parametrize("mechanism", sorted(_PAIRS))
    def test_same_seed_reports_are_byte_identical(self, mechanism):
        fixture = _PAIRS[mechanism]
        for seed in SEEDS[:2]:
            a = json.dumps(fixture(seed, ordered=False), sort_keys=True)
            b = json.dumps(fixture(seed, ordered=False), sort_keys=True)
            assert a == b

    def test_locked_counter_is_clean(self):
        # the lockset half of the hybrid, through the full traced stack
        for seed in SEEDS:
            det, sched, ins, box = _cooperative(seed)
            lock = ins.lock()

            def bump():
                with lock:
                    box.v = box.v + 1

            _run_threads(sched, ins, bump, bump)
            assert det.report()["total"] == 0

    def test_racy_report_carries_stacks_and_thread_names(self):
        caught = next(s for s in SEEDS
                      if _join_fixture(s, ordered=False)["total"] > 0)
        report = _join_fixture(caught, ordered=False)
        race = report["races"][0]
        assert race["var"].startswith("Box#")
        for side in (race["first"], race["second"]):
            assert side["thread_name"] in ("w1", "w2")
            assert side["stack"], "access stacks must be captured"

    def test_self_test_battery_is_green(self):
        verdicts = race_cli.run_self_test()
        assert verdicts["ok"], verdicts
        assert verdicts["racy_caught_seeds"]


class TestSchedulers:
    def test_deadlock_is_a_verdict_not_a_hang(self):
        det = RaceDetector(capture_stacks=False)
        sched = CooperativeScheduler(0)
        sched.bind(det)
        t0 = time.monotonic()
        with pytest.raises(SchedulerDeadlock):
            sched.run(lambda: sched.block_until(lambda: False))
        assert time.monotonic() - t0 < 4 * CooperativeScheduler.DEADLOCK_GRACE_S

    def test_perturb_decision_schedule_is_seed_deterministic(self,
                                                             monkeypatch):
        def decisions(seed):
            det = RaceDetector(capture_stacks=False)
            fuzz = PerturbFuzzer(seed, rate=0.5, max_sleep_s=0.001)
            fuzz.bind(det)
            rec = []
            monkeypatch.setattr(time, "sleep", rec.append)
            try:
                for _ in range(200):
                    fuzz.on_op("x")
            finally:
                monkeypatch.undo()
            return rec

        assert decisions(3) == decisions(3)
        assert decisions(3) != decisions(4)

    def test_fuzzer_stamps_its_seed_into_the_report(self):
        det = RaceDetector(capture_stacks=False)
        PerturbFuzzer(17).bind(det)
        assert det.report()["seed"] == 17


# ---------------------------------------------------------------------------
# the instrumented-sync layer: install/uninstall restore contract
# ---------------------------------------------------------------------------

def _fake_module(name="fakemod"):
    import types
    mod = types.ModuleType(name)
    src = textwrap.dedent("""
        import queue
        import threading
        from concurrent.futures import Future
        from dataclasses import dataclass, field

        @dataclass
        class Req:
            future: Future = field(default_factory=Future)

        def make_lock():
            return threading.Lock()

        def make_queue():
            return queue.Queue()
    """)
    exec(compile(src, f"{name}.py", "exec"), mod.__dict__)
    return mod


class TestInstrumentationInstall:
    def test_module_globals_swap_and_exact_restore(self):
        import queue as real_queue
        import threading as real_threading
        from concurrent.futures import Future as RealFuture

        mod = _fake_module()
        ins = Instrumentation(RaceDetector(capture_stacks=False))
        ins.install(modules=(mod,))
        assert mod.threading is ins.threading
        assert mod.queue is ins.queue
        assert mod.Future is ins.future_cls
        assert type(mod.make_lock()).__name__ == "_TracedLock"
        assert type(mod.make_queue()).__name__ == "TracedQueue"
        ins.uninstall()
        assert mod.threading is real_threading
        assert mod.queue is real_queue
        assert mod.Future is RealFuture
        assert type(mod.make_lock()) is type(real_threading.Lock())

    def test_dataclass_default_factory_swap_reaches_the_closure(self):
        # field(default_factory=Future) bakes the REAL class into the
        # generated __init__'s closure at class-definition time; the
        # install must patch Field metadata AND the closure cell, and the
        # uninstall must put the real class back in both places
        from concurrent.futures import Future as RealFuture

        mod = _fake_module()
        ins = Instrumentation(RaceDetector(capture_stacks=False))
        ins.install(modules=(mod,))
        assert type(mod.Req().future) is ins.future_cls
        ins.uninstall()
        assert type(mod.Req().future) is RealFuture
        assert mod.Req.__dataclass_fields__["future"].default_factory \
            is RealFuture
        for cell in mod.Req.__init__.__closure__ or ():
            v = cell.cell_contents
            assert not (isinstance(v, type) and issubclass(v, RealFuture)
                        and v is not RealFuture)

    def test_class_hooks_install_and_vanish_on_uninstall(self):
        class Plain:
            pass

        ins = Instrumentation(RaceDetector(capture_stacks=False))
        ins.track(Plain())
        assert "__setattr__" in vars(Plain)
        assert "__getattribute__" in vars(Plain)
        ins.uninstall()
        assert "__setattr__" not in vars(Plain)
        assert "__getattribute__" not in vars(Plain)

    def test_sync_valued_and_private_attrs_are_not_data(self):
        # reading the lock handle off an object IS synchronization; tracing
        # it would flag every guarded class on its own lock attribute
        det = RaceDetector(capture_stacks=False)
        ins = Instrumentation(det)

        class Holder:
            pass

        h = ins.track(Holder())
        try:
            h.lock = threading.Lock()
            h._race_scratch = 1
            h.n = 1
        finally:
            ins.uninstall()
        assert "Holder#0.n" in det._vars
        assert not any(v.endswith(".lock") for v in det._vars)
        assert not any("_race_" in v for v in det._vars)

    def test_active_context_manager_uninstalls_on_error(self):
        import threading as real_threading

        mod = _fake_module()
        ins = Instrumentation(RaceDetector(capture_stacks=False))
        with pytest.raises(RuntimeError):
            with ins.active(modules=(mod,)):
                assert mod.threading is ins.threading
                raise RuntimeError("boom")
        assert mod.threading is real_threading


# ---------------------------------------------------------------------------
# real-engine parity: instrumentation observes, never perturbs, and off is
# the byte-identical pre-instrumentation code path
# ---------------------------------------------------------------------------

class TestEngineParity:
    def test_instrumented_engine_is_bitwise_identical_and_race_clean(self):
        from concurrent.futures import Future as RealFuture

        import jax
        import numpy as np

        from iwae_replication_project_tpu.models import iwae as model
        from iwae_replication_project_tpu.serving import ServingEngine
        from iwae_replication_project_tpu.serving import batcher as mod_batcher
        from iwae_replication_project_tpu.serving import engine as mod_engine

        D = 32
        cfg = model.ModelConfig(x_dim=D, n_hidden_enc=(16, 8),
                                n_latent_enc=(8, 4), n_hidden_dec=(8, 16),
                                n_latent_dec=(8, D))
        params = model.init_params(jax.random.PRNGKey(0), cfg)
        x = (np.random.RandomState(0).rand(6, D) > 0.5).astype(np.float32)

        def run(instrumented, seed=0):
            ins = None
            if instrumented:
                det = RaceDetector(stack_depth=4)
                ins = Instrumentation(det,
                                      PerturbFuzzer(seed, rate=0.25,
                                                    max_sleep_s=0.001))
                ins.install(
                    modules=(mod_engine, mod_batcher),
                    classes=(ServingEngine, mod_batcher.MicroBatcher,
                             mod_batcher.InflightWindow))
            try:
                eng = ServingEngine(params=params, model_config=cfg, k=4,
                                    max_batch=8, timeout_s=30.0)
                eng.warmup(ops=("score",))
                out = eng.score(x)
                eng.stop()
            finally:
                if ins is not None:
                    ins.uninstall()
            return out, (ins.det.report() if ins else None)

        ref, _ = run(instrumented=False)
        on, report = run(instrumented=True)
        assert report["total"] == 0, report["races"][:2]
        assert np.array_equal(on, ref), \
            "instrumentation must observe, never perturb results"
        off, _ = run(instrumented=False)
        assert np.array_equal(off, ref), \
            "post-uninstall engine differs from the pre-install one"
        # the factory the uninstalled Request constructor calls is the
        # real Future again (Field metadata AND the __init__ closure)
        assert mod_batcher.Request.__dataclass_fields__[
            "future"].default_factory is RealFuture
        assert type(mod_batcher.Request(
            op="score", payload=None, k=1, seed=0, t_enqueue=0.0,
            deadline=None).future) is RealFuture


# ---------------------------------------------------------------------------
# static thread-escape analysis
# ---------------------------------------------------------------------------

def _classify(src, skip=()):
    tree = ast.parse(textwrap.dedent(src))
    cls = next(n for n in tree.body if isinstance(n, ast.ClassDef))
    return classify_class(cls, skip_attrs=set(skip))


class TestEscapeAnalysis:
    #: appended to CONFINED at the class-body indent level (before dedent)
    READ_N = ("\n            def read(self):\n"
              "                return self.n\n")

    CONFINED = """
        import threading

        class Worker:
            def __init__(self):
                self.n = 0

            def start(self):
                self.t = threading.Thread(target=self._loop)
                self.t.start()

            def _loop(self):
                self.n = self.n + 1
    """

    def test_single_thread_root_attr_is_confined(self):
        esc = _classify(self.CONFINED)
        assert esc.roots_of("n") == {"thread:_loop"}
        assert esc.confined("n")
        assert not esc.escaping("n")

    def test_external_reader_makes_it_escape(self):
        esc = _classify(self.CONFINED + self.READ_N)
        assert esc.roots_of("n") == {"thread:_loop", escape.EXTERNAL}
        assert esc.escaping("n") and not esc.confined("n")

    def test_reachability_follows_same_class_calls(self):
        esc = _classify("""
            import threading

            class W:
                def start(self):
                    threading.Thread(target=self._loop).start()

                def _loop(self):
                    self._step()

                def _step(self):
                    self.n = 1
        """)
        # _step's access lands in the thread root via the _loop -> _step
        # call edge; _step itself also counts as an external entry (the
        # analysis assumes any non-target method is publicly callable)
        assert "thread:_loop" in esc.roots_of("n")

    def test_done_callback_is_a_thread_root(self):
        esc = _classify("""
            class W:
                def arm(self, fut):
                    fut.add_done_callback(self._on_done)

                def _on_done(self, f):
                    self.done = True

                def poll(self):
                    return self.done
        """)
        assert esc.roots_of("done") == {"thread:_on_done", escape.EXTERNAL}
        assert esc.escaping("done")

    def test_queue_put_payload_is_a_handoff(self):
        esc = _classify("""
            class W:
                def push(self, q):
                    q.put(self.buf)
        """)
        assert escape.HANDOFF in esc.roots_of("buf")
        assert esc.escaping("buf")

    def test_thread_args_payload_is_a_handoff(self):
        esc = _classify("""
            import threading

            class W:
                def start(self):
                    threading.Thread(target=self._loop,
                                     args=(self.shared,)).start()

                def _loop(self, shared):
                    pass
        """)
        assert escape.HANDOFF in esc.roots_of("shared")

    def test_skip_attrs_hide_lock_attributes(self):
        esc = _classify(self.CONFINED + self.READ_N, skip=("n",))
        assert esc.roots_of("n") == {escape.EXTERNAL}   # the default

    def test_external_only_attr_neither_confined_nor_escaping(self):
        esc = _classify("""
            class W:
                def set(self, v):
                    self.v = v

                def get(self):
                    return self.v
        """)
        assert esc.roots_of("v") == {escape.EXTERNAL}
        assert not esc.confined("v") and not esc.escaping("v")


# ---------------------------------------------------------------------------
# the upgraded unlocked-shared-state rule (escape-aware) and the static
# leak pass, through the lint framework
# ---------------------------------------------------------------------------

def _lint(tmp_path, src, rel, **config_over):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    cfg = LintConfig(root=str(tmp_path), **config_over)
    return lint_paths([str(path)], cfg, root=str(tmp_path))


def _rules(findings):
    return [f.rule for f in findings]


class TestEscapeAwareLint:
    ESCAPING = """
        import threading

        class Worker:
            def __init__(self):
                self.n = 0

            def start(self):
                self.t = threading.Thread(target=self._loop)
                self.t.start()

            def _loop(self):
                self.n = self.n + 1

            def read(self):
                return self.n
    """

    def lint(self, tmp_path, src):
        return _lint(tmp_path, src, rel="conc/m.py",
                     concurrency_paths=["conc"])

    def test_never_guarded_escaping_write_fires(self, tmp_path):
        got = self.lint(tmp_path, self.ESCAPING)
        assert "unlocked-shared-state" in _rules(got)
        assert "escapes to multiple thread roots" in got[0].message

    def test_thread_confined_write_is_clean(self, tmp_path):
        confined = self.ESCAPING.replace(
            "            def read(self):\n"
            "                return self.n\n", "")
        assert self.lint(tmp_path, confined) == []


BAD_SPAN = """
    def handle(tracer, risky):
        span = tracer.start_span("req")
        risky()
        span.finish()
"""

GOOD_SPAN_FINALLY = """
    def handle(tracer, risky):
        span = tracer.start_span("req")
        try:
            risky()
        finally:
            span.finish()
"""

GOOD_SPAN_STRAIGHT_LINE = """
    def handle(tracer):
        span = tracer.start_span("req")
        ok = True
        span.finish()
        return ok
"""

NEVER_SUNK_SPAN = """
    def handle(tracer):
        span = tracer.start_span("req")
        return None
"""

DROPPED_FUTURE = """
    from concurrent.futures import Future

    def submit():
        Future()
"""

BAD_FUTURE = """
    from concurrent.futures import Future

    def submit(work):
        f = Future()
        work.validate()
        f.set_result(1)
        return f
"""

GOOD_FUTURE_EXCEPT_ALL = """
    from concurrent.futures import Future

    def submit(work):
        f = Future()
        try:
            work.run()
        except Exception as e:
            f.set_exception(e)
            raise
        f.set_result(1)
        return f
"""

GOOD_FUTURE_STORED_AT_BIRTH = """
    from concurrent.futures import Future

    def submit(self, key):
        self.pending[key] = Future()
"""

BAD_PIN = """
    def score(store, sig, xs):
        pin = store.pin_prefix(sig)
        out = xs.sum()
        pin.release()
        return out
"""

GOOD_PIN = """
    def score(store, sig, xs):
        pin = store.pin_prefix(sig)
        try:
            return run(pin, xs)
        finally:
            pin.release()
"""

SUPPRESSED_SPAN = """
    def handle(tracer, risky):
        span = tracer.start_span("req")  # iwaelint: disable=leaked-span -- risky() is exception-free by construction (pure dict lookup); the straight-line finish below always runs
        risky()
        span.finish()
"""


class TestLeakPass:
    def lint(self, tmp_path, src):
        return _lint(tmp_path, src, rel="leak/m.py", leak_paths=["leak"],
                     select=["leaked-future", "leaked-span", "leaked-pin"])

    def test_span_leaks_when_a_call_can_raise_before_finish(self, tmp_path):
        got = self.lint(tmp_path, BAD_SPAN)
        assert _rules(got) == ["leaked-span"]
        assert "leaks if line" in got[0].message

    def test_span_protected_by_finally_is_clean(self, tmp_path):
        assert self.lint(tmp_path, GOOD_SPAN_FINALLY) == []

    def test_span_with_nothing_raising_before_finish_is_clean(self,
                                                              tmp_path):
        assert self.lint(tmp_path, GOOD_SPAN_STRAIGHT_LINE) == []

    def test_span_with_no_sink_at_all_fires(self, tmp_path):
        got = self.lint(tmp_path, NEVER_SUNK_SPAN)
        assert _rules(got) == ["leaked-span"]
        assert "never completed" in got[0].message

    def test_unbound_future_fires(self, tmp_path):
        got = self.lint(tmp_path, DROPPED_FUTURE)
        assert _rules(got) == ["leaked-future"]
        assert "never bound" in got[0].message

    def test_future_leaks_across_a_raising_call(self, tmp_path):
        assert _rules(self.lint(tmp_path, BAD_FUTURE)) == ["leaked-future"]

    def test_future_with_except_all_completion_is_clean(self, tmp_path):
        assert self.lint(tmp_path, GOOD_FUTURE_EXCEPT_ALL) == []

    def test_future_stored_at_birth_is_a_handoff(self, tmp_path):
        assert self.lint(tmp_path, GOOD_FUTURE_STORED_AT_BIRTH) == []

    def test_pin_pair(self, tmp_path):
        assert _rules(self.lint(tmp_path, BAD_PIN)) == ["leaked-pin"]
        assert self.lint(tmp_path, GOOD_PIN) == []

    def test_justified_suppression_silences_a_leak_finding(self, tmp_path):
        assert self.lint(tmp_path, SUPPRESSED_SPAN) == []

    def test_future_with_ctor_args_is_not_an_acquisition(self, tmp_path):
        # Future(x) is some other library's constructor, not the stdlib
        # zero-arg acquisition this pass owns
        src = """
            def submit(x):
                f = Future(x)
                work()
        """
        assert self.lint(tmp_path, src) == []

    def test_shipped_leak_paths_are_clean(self):
        # the CI invocation: the configured serving control plane passes
        cfg, _ = load_config(REPO)
        cfg.select = ["leaked-future", "leaked-span", "leaked-pin"]
        assert lint_paths(cfg.leak_paths, cfg, root=REPO) == []


# ---------------------------------------------------------------------------
# CLI exit contract
# ---------------------------------------------------------------------------

class TestRaceCli:
    def _run(self, *args, cwd=REPO):
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        return subprocess.run(
            [sys.executable, "-m",
             "iwae_replication_project_tpu.analysis.race", *args],
            cwd=cwd, env=env, capture_output=True, text=True)

    def _leak_tree(self, tmp_path, src):
        # --no-config uses the built-in leak_paths; mirror one of them
        # under a scratch root so the rules are in scope for the file
        rel = "iwae_replication_project_tpu/serving/engine.py"
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
        return rel

    def test_clean_file_exits_0(self, tmp_path):
        rel = self._leak_tree(tmp_path, "x = 1\n")
        r = self._run("--no-config", rel, cwd=tmp_path)
        assert r.returncode == 0, r.stderr
        assert "leak pass clean" in r.stdout

    def test_findings_exit_1_with_json(self, tmp_path):
        rel = self._leak_tree(tmp_path, BAD_SPAN)
        r = self._run("--no-config", "--format", "json", rel, cwd=tmp_path)
        assert r.returncode == 1, r.stderr
        payload = json.loads(r.stdout)
        assert payload["counts"] == {"leaked-span": 1}

    def test_missing_path_exits_2(self, tmp_path):
        r = self._run("--no-config", "does_not_exist.py", cwd=tmp_path)
        assert r.returncode == 2
        assert "error" in r.stderr

    def test_list_rules_exits_0(self):
        r = self._run("--list-rules")
        assert r.returncode == 0
        for rule in ("leaked-future", "leaked-span", "leaked-pin"):
            assert rule in r.stdout

    def test_self_test_reports_verdicts_in_json(self, tmp_path):
        rel = self._leak_tree(tmp_path, "x = 1\n")
        r = self._run("--no-config", "--self-test", "--format", "json",
                      rel, cwd=tmp_path)
        assert r.returncode == 0, r.stderr
        st = json.loads(r.stdout)["self_test"]
        assert st["ok"] and st["racy_caught_seeds"]

    def test_shipped_tree_is_clean_via_configured_paths(self):
        # the exact CI stage: pyproject leak_paths, exit 0
        r = self._run()
        assert r.returncode == 0, r.stdout + r.stderr
