"""Sanitizer-profile tier-1 tests (ISSUE 2 runtime-sanitizer layer).

Run plain, these are ordinary fast tests over the production hot paths. Run
as ``pytest --sanitize -m sanitize``, conftest wraps each CALL phase in
``jax.transfer_guard("disallow")`` + ``jax.debug_nans``: the test body must
perform **zero implicit host<->device transfers** (on jax 0.4.x even
``x + 1`` eagerly commits the scalar, so the only way to pass is the
production discipline itself — fully-jitted programs over inputs committed in
fixtures) and any NaN produced by any primitive raises immediately. This is
the dynamic twin of the ``host-sync`` lint rule (analysis/rules/host.py): the
lint rule proves hot-path *modules* contain no implicit-sync calls, this
profile proves the hot-path *programs* execute without one.

Inputs are committed in module-scope fixtures (setup runs outside the guard —
minting a key is itself an implicit int32 commit); fetches use np.asarray,
which the guard treats as explicit.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.evaluation.metrics import (
    SCALAR_NAMES,
    dataset_scalars,
)
from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.training import create_train_state, make_adam
from iwae_replication_project_tpu.training.epoch import make_epoch_fn
from iwae_replication_project_tpu.training.train_step import make_train_step

pytestmark = pytest.mark.sanitize

N, B, D = 96, 32, 784


@pytest.fixture(scope="module")
def dev():
    """Every host->device commit happens here, in setup, outside the guard:
    tests receive device-resident state/data/pre-split keys only."""
    cfg = model.ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                            n_hidden_dec=(16,), n_latent_dec=(D,))
    spec = ObjectiveSpec("IWAE", k=4)
    opt = make_adam(eps=1e-4)
    keys = jax.random.split(jax.random.PRNGKey(0), 4)
    x = jnp.asarray((np.random.RandomState(0).rand(N, D) > 0.5)
                    .astype(np.float32))
    state = create_train_state(keys[0], cfg, optimizer=opt)
    # pre-shaped views and pre-indexed keys: even an eager x[:B] / keys[1] in
    # the test body dispatches a slice whose index scalars are implicit commits
    return {"cfg": cfg, "spec": spec, "opt": opt, "key_eval": keys[1],
            "x": x, "xb": x[:B], "batches": x.reshape(3, B, D),
            "state": state}


def test_sanitizer_is_armed(request, dev):
    """Meta-test: with --sanitize the wiring is actually live — an implicit
    scalar commit raises, and a NaN-producing jitted program raises
    FloatingPointError instead of silently propagating."""
    if not request.config.getoption("--sanitize"):
        pytest.skip("plain profile: sanitizer guards not armed")
    with pytest.raises(Exception, match="[Dd]isallow"):
        jnp.ones(())  # implicit host->device commit of the fill scalar
    # x is in {0,1}; x - 2 < 0, so log produces NaN on every element.
    # debug_nans detects it and re-runs the program un-jitted to localize;
    # that eager re-run commits the 2.0 scalar and trips the transfer guard
    # first on this jax version — either error proves the NaN was caught.
    with pytest.raises(Exception, match="(?i)nan|disallow"):
        np.asarray(jax.jit(lambda a: jnp.log(a - 2.0))(dev["x"]))


def test_train_step_under_guard(dev):
    """One jitted train step: dispatch, donate-free, explicit fetch; finite
    loss and params. debug_nans checks every primitive inside the grad."""
    step = make_train_step(dev["spec"], dev["cfg"], optimizer=dev["opt"],
                           donate=False)
    state, metrics = step(dev["state"], dev["xb"])
    assert np.isfinite(np.asarray(metrics["loss"]))
    leaves = jax.tree.leaves(state.params)
    assert all(np.isfinite(np.asarray(leaf)).all() for leaf in leaves)


def test_epoch_scan_under_guard(dev):
    """The production whole-epoch lax.scan program (the hot path the
    host-sync lint rule protects) runs start-to-finish with zero implicit
    transfers; per-batch losses come back finite."""
    fn = make_epoch_fn(dev["spec"], dev["cfg"], N, B, optimizer=dev["opt"],
                       donate=False)
    state, losses = fn(dev["state"], dev["x"])
    out = np.asarray(losses)
    assert out.shape == (N // B,)
    assert np.isfinite(out).all()


def test_multi_epoch_block_under_guard(dev):
    """The PASS_BLOCK-style multi-epoch dispatch (scan over scans) — the
    program the long Burda stages actually execute."""
    fn = make_epoch_fn(dev["spec"], dev["cfg"], N, B, optimizer=dev["opt"],
                       donate=False, epochs_per_call=2)
    state, losses = fn(dev["state"], dev["x"])
    out = np.asarray(losses)
    assert out.shape == (2 * (N // B),)
    assert np.isfinite(out).all()


def test_fused_eval_suite_under_guard(dev):
    """The one-dispatch fused eval program (all 7 reference scalars): the
    k=5000-style streaming path in miniature, under transfer guard."""
    scalars = dataset_scalars(dev["state"].params, dev["cfg"],
                              dev["key_eval"], dev["batches"], 4, 8, 4)
    out = np.asarray(scalars)
    assert out.shape == (len(SCALAR_NAMES),)
    assert np.isfinite(out).all()


def test_epoch_with_diagnostics_under_guard(dev):
    """The diagnostics-enabled epoch program: the telemetry layer's central
    claim is that grad-SNR accumulation adds device reductions and ZERO host
    syncs — the transfer guard is the proof."""
    from iwae_replication_project_tpu.telemetry.diagnostics import (
        DiagnosticsConfig)
    fn = make_epoch_fn(dev["spec"], dev["cfg"], N, B, optimizer=dev["opt"],
                       donate=False,
                       diagnostics=DiagnosticsConfig(snr_window=2))
    state, (losses, diag) = fn(dev["state"], dev["x"])
    assert np.isfinite(np.asarray(losses)).all()
    for k, v in diag.items():
        assert np.isfinite(np.asarray(v)), k


def test_estimator_diagnostics_under_guard(dev):
    """The per-eval weight-space diagnostics program (ESS / log-weight
    variance / KL / active units) under transfer guard — same zero-host-sync
    contract as the fused eval suite it rides next to."""
    from iwae_replication_project_tpu.telemetry.diagnostics import (
        DiagnosticsConfig, estimator_diagnostics)
    out = estimator_diagnostics(dev["state"].params, dev["cfg"],
                                dev["key_eval"], dev["batches"], 4,
                                DiagnosticsConfig())
    for k, v in out.items():
        assert np.isfinite(np.asarray(v)), k


@pytest.fixture(scope="module")
def serve_eng(dev):
    """A warmed serving engine (setup outside the guard: construction commits
    params + base key, warmup compiles the bucket ladder)."""
    from iwae_replication_project_tpu.serving import ServingEngine

    eng = ServingEngine(params=dev["state"].params, model_config=dev["cfg"],
                        k=4, max_batch=4, timeout_s=None)
    eng.warmup(ops=("score",))
    return {"eng": eng, "rows": np.asarray(dev["xb"][:3])}


def test_serving_dispatch_under_guard(serve_eng):
    """The engine's public dispatch path — queue -> coalesce -> pad-to-bucket
    -> AOT dispatch -> slice — on the warm path: every transfer it performs
    is explicit (device_put for payloads/seeds, np.asarray for results), so
    a warm serve round runs clean under transfer_guard('disallow'), and
    debug_nans certifies the per-row score program NaN-free."""
    out = serve_eng["eng"].score(serve_eng["rows"])
    assert out.shape == (3,)
    assert np.isfinite(out).all()
