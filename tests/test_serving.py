"""Serving subsystem tests: ladder, batcher policy (fake clock), padded-bucket
parity, robustness (timeout / backpressure), warm-path zero-compile, and the
checkpoint -> engine path. The synthetic load sweep lives in the slow profile.
"""

import json
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.serving import (
    BucketLadder,
    EngineOverloaded,
    MicroBatcher,
    Request,
    RequestTimeout,
    ServingEngine,
)
from iwae_replication_project_tpu.serving import programs

D = 32
TINY = dict(n_hidden_enc=(16, 8), n_latent_enc=(8, 4),
            n_hidden_dec=(8, 16), n_latent_dec=(8, D))


@pytest.fixture(scope="module")
def tiny():
    cfg = model.ModelConfig(x_dim=D, **TINY)
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    x = (np.random.RandomState(0).rand(17, D) > 0.5).astype(np.float32)
    return {"cfg": cfg, "params": params, "x": x}


def make_engine(tiny, **kw):
    kw.setdefault("k", 4)
    kw.setdefault("max_batch", 8)
    kw.setdefault("timeout_s", 30.0)
    return ServingEngine(params=tiny["params"], model_config=tiny["cfg"], **kw)


# ---------------------------------------------------------------------------
# bucket ladder
# ---------------------------------------------------------------------------

def test_ladder_powers_of_two():
    lad = BucketLadder.powers_of_two(64)
    assert lad.buckets == (1, 2, 4, 8, 16, 32, 64)
    assert lad.bucket_for(1) == 1
    assert lad.bucket_for(3) == 4
    assert lad.bucket_for(64) == 64
    # non-power-of-two max becomes its own top rung
    assert BucketLadder.powers_of_two(48).buckets == (1, 2, 4, 8, 16, 32, 48)
    with pytest.raises(ValueError):
        lad.bucket_for(65)
    with pytest.raises(ValueError):
        lad.bucket_for(0)
    with pytest.raises(ValueError):
        BucketLadder((4, 2))


def test_ladder_pad_rows():
    lad = BucketLadder.powers_of_two(8)
    rows = np.ones((3, 5), np.float32)
    padded = lad.pad_rows(rows, 4)
    assert padded.shape == (4, 5)
    assert np.array_equal(padded[:3], rows) and np.all(padded[3] == 0)
    assert lad.pad_rows(rows, 3) is rows  # exact fit: no copy
    with pytest.raises(ValueError):
        lad.pad_rows(rows, 2)


# ---------------------------------------------------------------------------
# micro-batcher policy under a fake clock
# ---------------------------------------------------------------------------

class FakeClock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def _req(op="score", k=4, seed=0, t=100.0, deadline=None):
    return Request(op=op, payload=np.zeros(D, np.float32), k=k, seed=seed,
                   t_enqueue=t, deadline=deadline)


def test_batcher_max_batch_flush():
    clk = FakeClock()
    b = MicroBatcher(max_batch=4, max_wait_us=10_000, queue_limit=64,
                     clock=clk)
    for i in range(9):
        b.submit(_req(seed=i, t=clk.t))
    expired, batches = b.poll()  # no time has passed: only full batches go
    assert expired == []
    assert [len(x) for x in batches] == [4, 4]
    assert b.pending == 1
    assert [r.seed for r in batches[0]] == [0, 1, 2, 3]  # FIFO preserved


def test_batcher_max_wait_flush():
    clk = FakeClock()
    b = MicroBatcher(max_batch=8, max_wait_us=2_000, queue_limit=64,
                     clock=clk)
    b.submit(_req(seed=0, t=clk.t))
    assert b.poll() == ([], [])          # policy not met yet
    assert b.next_event() == pytest.approx(100.0 + 0.002)
    clk.t += 0.0025                       # > max_wait: lone request flushes
    expired, batches = b.poll()
    assert expired == [] and [len(x) for x in batches] == [1]
    assert b.pending == 0


def test_batcher_groups_do_not_mix():
    clk = FakeClock()
    b = MicroBatcher(max_batch=8, max_wait_us=0, queue_limit=64, clock=clk)
    b.submit(_req(k=4, seed=0, t=clk.t))
    b.submit(_req(k=8, seed=1, t=clk.t))
    b.submit(_req(op="encode", k=4, seed=2, t=clk.t))
    _, batches = b.poll()
    assert sorted((x[0].group, len(x)) for x in batches) == [
        (("encode", 4), 1), (("score", 4), 1), (("score", 8), 1)]


def test_batcher_timeout_expiry():
    clk = FakeClock()
    b = MicroBatcher(max_batch=4, max_wait_us=1_000_000, queue_limit=64,
                     clock=clk)
    b.submit(_req(seed=0, t=clk.t, deadline=clk.t + 0.5))
    b.submit(_req(seed=1, t=clk.t, deadline=clk.t + 5.0))
    clk.t += 1.0
    expired, batches = b.poll()
    assert [r.seed for r in expired] == [0]
    assert [len(x) for x in batches] == [1]  # survivor flushes via max-wait
    assert b.pending == 0


def test_batcher_backpressure_bound():
    b = MicroBatcher(max_batch=4, max_wait_us=0, queue_limit=2,
                     clock=FakeClock())
    b.submit(_req(seed=0))
    b.submit(_req(seed=1))
    with pytest.raises(EngineOverloaded):
        b.submit(_req(seed=2))
    assert b.pending == 2


def test_batcher_force_flush():
    clk = FakeClock()
    b = MicroBatcher(max_batch=8, max_wait_us=10_000_000, queue_limit=64,
                     clock=clk)
    for i in range(3):
        b.submit(_req(seed=i, t=clk.t))
    assert b.poll() == ([], [])
    _, batches = b.poll(force=True)
    assert [len(x) for x in batches] == [3]


# ---------------------------------------------------------------------------
# padded-bucket parity: the engine's results ARE the model's results
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n", [1, 3, 7, 17])
def test_padded_bucket_parity_score(tiny, n):
    """Engine score over a ragged batch == direct unpadded program call,
    bitwise (same dtype, same seeds): padding rows never leak."""
    eng = make_engine(tiny, max_batch=32)
    x = tiny["x"][:n]
    got = eng.score(x)
    direct = np.asarray(programs.score_rows(
        tiny["params"], eng.cfg, eng._base_key,
        jnp.arange(n, dtype=jnp.int32), jnp.asarray(x), 4))
    assert got.dtype == direct.dtype
    assert np.array_equal(got, direct)


@pytest.mark.parametrize("n", [1, 3, 7, 17])
def test_padded_bucket_parity_encode(tiny, n):
    eng = make_engine(tiny, max_batch=32)
    x = tiny["x"][:n]
    got = eng.encode(x)
    direct = np.asarray(programs.encode_rows(
        tiny["params"], eng.cfg, eng._base_key,
        jnp.arange(n, dtype=jnp.int32), jnp.asarray(x), 4))
    assert got.dtype == direct.dtype
    assert np.array_equal(got, direct)


def test_padded_bucket_parity_decode(tiny):
    eng = make_engine(tiny, max_batch=8)
    h = np.random.RandomState(1).randn(3, 4).astype(np.float32)
    got = eng.decode(h)
    direct = np.asarray(programs.decode_rows(
        tiny["params"], eng.cfg, eng._base_key,
        jnp.arange(3, dtype=jnp.int32), jnp.asarray(h)))
    assert np.array_equal(got, direct)
    assert got.shape == (3, D) and got.min() > 0 and got.max() < 1


def test_single_row_request(tiny):
    eng = make_engine(tiny)
    s = eng.score(tiny["x"][0])
    assert s.shape == () and np.isfinite(s)
    e = eng.encode(tiny["x"][0])
    assert e.shape == (4,)


# ---------------------------------------------------------------------------
# robustness: timeout, backpressure, dispatch errors
# ---------------------------------------------------------------------------

def test_engine_timeout_is_per_request_error(tiny):
    eng = make_engine(tiny, timeout_s=0.0)  # every request expires on poll
    fut = eng.submit("score", tiny["x"][0])
    eng.flush()
    with pytest.raises(RequestTimeout):
        fut.result(timeout=5)
    assert eng.metrics.snapshot()["counters"]["timeouts"] == 1
    # the engine survives and keeps serving once the deadline allows
    eng.timeout_s = None
    assert np.isfinite(eng.score(tiny["x"][0]))


def test_engine_backpressure_sheds(tiny):
    eng = make_engine(tiny, queue_limit=2)
    eng.submit("score", tiny["x"][0])
    eng.submit("score", tiny["x"][1])
    with pytest.raises(EngineOverloaded):
        eng.submit("score", tiny["x"][2])
    assert eng.metrics.snapshot()["counters"]["shed"] == 1
    eng.flush()  # queued work still completes


def test_cancelled_future_does_not_kill_dispatch(tiny):
    """A client cancelling its pending Future must not blow up the dispatch
    path (InvalidStateError on completion) — remaining requests in the batch
    still complete, and the cancelled one is not counted as completed."""
    eng = make_engine(tiny)
    f1 = eng.submit("score", tiny["x"][0])
    assert f1.cancel()
    f2 = eng.submit("score", tiny["x"][1])
    eng.flush()
    assert np.isfinite(np.asarray(f2.result(timeout=60)))
    assert f1.cancelled()
    c = eng.metrics.snapshot()["counters"]
    assert c["completed"] == 1 and c["errors"] == 0


def test_engine_rejects_bad_requests(tiny):
    eng = make_engine(tiny)
    with pytest.raises(ValueError, match="unknown op"):
        eng.submit("frobnicate", tiny["x"][0])
    with pytest.raises(ValueError, match="features"):
        eng.submit("score", np.zeros(7, np.float32))


def test_background_thread_round_trip(tiny):
    eng = make_engine(tiny, max_wait_us=500.0)
    eng.start()
    try:
        futs = [eng.submit("score", r) for r in tiny["x"][:5]]
        out = np.array([f.result(timeout=60) for f in futs])
    finally:
        eng.stop()
    direct = eng.score(tiny["x"][:5])  # inline path, fresh seeds
    assert out.shape == (5,) and np.isfinite(out).all()
    # same rows, different request seeds -> close but not identical streams
    assert np.all(np.abs(out - direct) < 10.0)


# ---------------------------------------------------------------------------
# the two-stage pipeline: window mechanics, parity, error routing, drain
# ---------------------------------------------------------------------------

def test_inflight_window_mechanics():
    """InflightWindow is pure synchronization: slot accounting (acquire/
    release/done), FIFO hand-off (commit/pop), forced acquire on abort
    (shutdown must not lose batches), drain-then-None pop. No device, no
    clock, no threads needed."""
    from iwae_replication_project_tpu.serving.batcher import InflightWindow

    w = InflightWindow(2)
    assert w.acquire() and w.acquire()
    assert w.inflight == 2
    # saturated + abort: the slot is still taken (forced), reported False
    assert w.acquire(abort=lambda: True) is False
    assert w.inflight == 3
    w.release()                      # a failed launch gives its slot back
    assert w.inflight == 2
    w.commit("a")
    w.commit("b")
    assert w.pop() == "a"            # dispatch order
    w.done()
    assert w.inflight == 1
    assert w.pop(stop=lambda: True) == "b"   # drain: items before None
    assert w.pop(stop=lambda: True) is None
    with pytest.raises(ValueError):
        InflightWindow(0)


def test_serial_vs_pipelined_bitwise_parity(tiny):
    """A fresh serial engine (max_inflight=0) and a fresh pipelined engine
    fed the identical ragged stream in identical submit order mint identical
    per-request seeds — so per-request results must be BITWISE equal, no
    matter how differently the two modes coalesced, padded, or overlapped
    the work. Pipelining changes when stages run, never what they compute."""
    def run(max_inflight):
        eng = make_engine(tiny, max_batch=8, max_wait_us=200.0,
                          max_inflight=max_inflight)
        eng.start()
        try:
            futs = []
            for n in (1, 3, 7, 2, 8, 5, 1, 4):
                for r in tiny["x"][:n]:
                    futs.append(eng.submit("score", r))
            return [np.asarray(f.result(timeout=120)) for f in futs]
        finally:
            eng.stop()

    serial, pipelined = run(0), run(2)
    assert len(serial) == len(pipelined) == 31
    for a, b in zip(serial, pipelined):
        assert a.dtype == b.dtype and np.array_equal(a, b)


def test_dispatch_exception_routes_to_affected_batch(tiny, monkeypatch):
    """An enqueue-time failure lands in exactly the affected batch's futures
    (here: the k=8 coalescing group); other groups complete normally and the
    engine keeps serving."""
    from iwae_replication_project_tpu.serving.engine import ServingEngine

    eng = make_engine(tiny)
    real = ServingEngine._launch

    def boom(self, batch):
        if batch[0].k == 8:
            raise RuntimeError("boom")
        return real(self, batch)

    monkeypatch.setattr(ServingEngine, "_launch", boom)
    good = [eng.submit("score", r, k=4) for r in tiny["x"][:3]]
    bad = [eng.submit("score", r, k=8) for r in tiny["x"][3:5]]
    eng.flush()
    for f in good:
        assert np.isfinite(f.result(timeout=60))
    for f in bad:
        with pytest.raises(RuntimeError, match="boom"):
            f.result(timeout=60)
    c = eng.metrics.snapshot()["counters"]
    assert c["errors"] == 2 and c["completed"] == 3


class _PoisonOut:
    """A fake device result whose host fetch raises — the deferred-error
    shape: async dispatch succeeded, the failure surfaces at the D2H."""

    def __array__(self, *a, **kw):
        raise RuntimeError("poisoned fetch")


def test_fetch_exception_routes_to_affected_inflight_batch(tiny, monkeypatch):
    """A failure surfacing at the completion stage's fetch is routed to
    exactly that in-flight batch's futures; batches before/after complete,
    and the completion thread survives."""
    from iwae_replication_project_tpu.serving.engine import ServingEngine

    eng = make_engine(tiny, max_inflight=2, max_wait_us=200.0)
    real = ServingEngine._launch

    def poison(self, batch):
        inf = real(self, batch)
        if inf.k == 8:
            inf.out = _PoisonOut()
        return inf

    monkeypatch.setattr(ServingEngine, "_launch", poison)
    eng.start()
    try:
        bad = [eng.submit("score", r, k=8) for r in tiny["x"][:2]]
        good = [eng.submit("score", r, k=4) for r in tiny["x"][:3]]
        for f in bad:
            with pytest.raises(RuntimeError, match="poisoned"):
                f.result(timeout=60)
        for f in good:
            assert np.isfinite(f.result(timeout=60))
    finally:
        eng.stop()
    c = eng.metrics.snapshot()["counters"]
    assert c["errors"] == 2 and c["completed"] == 3
    assert eng.metrics.inflight == 0


def test_stop_drains_work_in_flight(tiny, monkeypatch):
    """stop() with batches queued AND in flight completes every future —
    queued work is flushed, the window is drained, nothing is lost. A slowed
    fetch guarantees the window is non-empty when stop() lands."""
    from iwae_replication_project_tpu.serving.engine import ServingEngine

    real = ServingEngine._fetch

    def slow_fetch(self, out):
        time.sleep(0.02)
        return real(self, out)

    monkeypatch.setattr(ServingEngine, "_fetch", slow_fetch)
    eng = make_engine(tiny, max_inflight=2, max_wait_us=100.0)
    eng.start()
    futs = [eng.submit("score", r) for r in tiny["x"]]
    eng.stop()                       # immediately: work is still in flight
    assert all(f.done() for f in futs)
    out = np.stack([f.result(timeout=0) for f in futs])
    assert out.shape == (17,) and np.isfinite(out).all()
    c = eng.metrics.snapshot()["counters"]
    assert c["completed"] == 17 and c["errors"] == 0 and c["timeouts"] == 0
    assert eng.metrics.inflight == 0


def _spin_until(pred, timeout_s=10.0):
    t0 = time.monotonic()
    while not pred():
        if time.monotonic() - t0 > timeout_s:
            raise AssertionError("condition not reached in time")
        time.sleep(0.002)


def test_backpressure_caps_inflight_and_feeds_shedding(tiny, monkeypatch):
    """With the window saturated (completion gated shut), the dispatcher
    must stop launching — at most max_inflight batches are ever enqueued on
    the device — and the stalled queue then sheds at queue_limit. Fake
    launch/fetch: no device, no real device timing in the loop."""
    import threading

    from iwae_replication_project_tpu.serving.engine import (
        ServingEngine, _InFlight)

    launches = []
    gate = threading.Event()

    def fake_launch(self, batch):
        launches.append(len(batch))
        t = self._clock()
        for r in batch:
            r.t_dispatch = t
        return _InFlight(batch=batch, op=batch[0].op, k=batch[0].k,
                         bucket=len(batch), out=None)

    def fake_fetch(self, out):
        assert gate.wait(timeout=30)
        return np.zeros((64,), np.float32)

    monkeypatch.setattr(ServingEngine, "_launch", fake_launch)
    monkeypatch.setattr(ServingEngine, "_fetch", fake_fetch)
    eng = make_engine(tiny, max_inflight=1, max_wait_us=0.0, queue_limit=4)
    eng.start()
    try:
        futs = [eng.submit("score", tiny["x"][0])]
        _spin_until(lambda: len(launches) == 1)   # batch 1 is in flight
        # more submissions: the dispatcher may pop them, but must NOT launch
        # past the window while the completion stage is gated shut
        futs += [eng.submit("score", r) for r in tiny["x"][1:3]]
        time.sleep(0.1)
        assert len(launches) == 1
        # acquire() blocks BEFORE taking the slot: exactly one batch holds
        # the window while the completion stage is gated
        assert eng._window.inflight == 1
        # backpressure reaches the caller: the queue fills and sheds
        shed = 0
        for _ in range(eng._batcher.queue_limit + 3):
            try:
                futs.append(eng.submit("score", tiny["x"][3]))
            except EngineOverloaded:
                shed += 1
                break
        assert shed == 1, "saturated pipeline never shed"
        gate.set()                    # release: everything drains
        for f in futs:
            assert f.result(timeout=60) is not None
    finally:
        gate.set()
        eng.stop()
    c = eng.metrics.snapshot()["counters"]
    assert c["shed"] == 1
    assert c["completed"] == len(futs)
    assert eng.metrics.inflight == 0


# ---------------------------------------------------------------------------
# warm path: zero compiles across a ragged stream after warmup
# ---------------------------------------------------------------------------

def test_warmup_then_zero_compiles(tiny):
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    eng = make_engine(tiny, max_batch=8)
    warm = eng.warmup(ops=("score",))
    assert warm["programs"] == len(eng.ladder.buckets)
    s0 = cache_stats()
    for n in (1, 3, 7, 2, 8, 5, 1, 4):
        eng.score(tiny["x"][:n])
    d = stats_delta(s0)
    assert d["aot_misses"] == 0, "ragged stream compiled after warmup"
    c = eng.metrics.snapshot()["counters"]
    assert c["aot_misses"] == 0 and c["recompiles"] == 0
    assert c["aot_hits"] == 8


def test_metrics_accounting(tiny):
    eng = make_engine(tiny, max_batch=8)
    eng.score(tiny["x"][:3])  # bucket 4: one padding row
    snap = eng.metrics.snapshot()
    c = snap["counters"]
    assert c["submitted"] == c["completed"] == 3
    assert c["dispatches"] == 1
    assert c["real_rows"] == 3 and c["padded_rows"] == 1
    assert snap["padding_waste"] == pytest.approx(0.25)
    lat = snap["latency"]["score/b4"]
    assert lat["count"] == 3
    assert lat["p50_s"] is not None and lat["p99_s"] >= lat["p50_s"]
    # the pipeline split schema: queue-wait + device-wait per (op, bucket),
    # recorded on the serial path too (t_dispatch is stamped either way),
    # and the in-flight gauge (0: nothing outstanding after a blocking call)
    assert snap["inflight"] == 0
    assert snap["queue_wait"]["score/b4"]["count"] == 3
    assert snap["device_wait"]["score/b4"]["count"] == 3
    flat = eng.metrics.flat()
    assert flat["latency/score/b4/count"] == 3.0
    assert flat["queue_wait/score/b4/count"] == 3.0
    assert flat["device_wait/score/b4/count"] == 3.0
    assert flat["inflight"] == 0.0
    assert all(isinstance(v, float) for v in flat.values())


def test_latency_histogram_percentiles():
    from iwae_replication_project_tpu.serving.metrics import LatencyHistogram

    h = LatencyHistogram()
    assert h.percentile(0.5) is None
    for ms in range(1, 101):  # 1..100 ms uniform
        h.record(ms / 1000.0)
    # log-bin upper bounds: within one bin (~33%) of the true quantile
    assert 0.04 < h.percentile(0.50) < 0.09
    assert 0.08 < h.percentile(0.99) < 0.17
    assert h.summary()["count"] == 100


# ---------------------------------------------------------------------------
# construction paths: facade, checkpoint, zoo
# ---------------------------------------------------------------------------

def test_facade_serving_engine(tiny):
    from iwae_replication_project_tpu.api import FlexibleModel

    mdl = FlexibleModel([16, 8], [8, 16], [8, 4], [8, D],
                        dataset_bias=None, loss_function="IWAE", k=4,
                        backend="jax").compile()
    eng = mdl.serving_engine(max_batch=4)
    assert eng.k == 4
    out = eng.score((np.random.RandomState(2).rand(2, D) > 0.5)
                    .astype(np.float32))
    assert out.shape == (2,) and np.isfinite(out).all()


def test_eager_backend_has_no_serving():
    from iwae_replication_project_tpu.api import FlexibleModel

    torch = pytest.importorskip("torch")  # noqa: F841
    mdl = FlexibleModel([16], [16], [4], [D], dataset_bias=None,
                        backend="torch")
    with pytest.raises(NotImplementedError, match="backend='jax'"):
        mdl.serving_engine()


def test_engine_requires_a_source(tiny):
    with pytest.raises(ValueError, match="checkpoint directory"):
        ServingEngine()
    with pytest.raises(ValueError, match="compile"):
        ServingEngine(object())


def test_engine_from_checkpoint(tmp_path):
    """The ServingEngine(checkpoint_dir) path: restore the stored config +
    weights and serve bitwise-identically to an engine built from the same
    params directly."""
    from iwae_replication_project_tpu.training import (
        create_train_state, make_adam)
    from iwae_replication_project_tpu.utils.checkpoint import save_checkpoint
    from iwae_replication_project_tpu.utils.config import ExperimentConfig

    ecfg = ExperimentConfig(n_hidden_encoder=(8,), n_latent_encoder=(4,),
                            n_hidden_decoder=(8,), n_latent_decoder=(784,),
                            k=7, compute_dtype=None, fused_likelihood=False)
    state = create_train_state(jax.random.PRNGKey(3), ecfg.model_config(),
                               optimizer=make_adam(eps=ecfg.adam_eps))
    run_dir = str(tmp_path / "run")
    save_checkpoint(run_dir, 0, state, stage=1, config_json=ecfg.to_json())

    # k unspecified -> the stored config's training k, not a hardcoded 50
    assert ServingEngine(run_dir, max_batch=1).k == 7

    eng = ServingEngine(run_dir, k=3, max_batch=4)
    x = (np.random.RandomState(4).rand(2, 784) > 0.5).astype(np.float32)
    got = eng.score(x)
    ref = ServingEngine(params=state.params,
                        model_config=ecfg.model_config(), k=3,
                        max_batch=4).score(x)
    assert np.array_equal(got, ref)

    with pytest.raises(FileNotFoundError):
        ServingEngine(str(tmp_path / "nope"))


def test_zoo_serving_engine():
    from iwae_replication_project_tpu import zoo
    from iwae_replication_project_tpu.utils.config import ExperimentConfig

    ecfg = ExperimentConfig(n_hidden_encoder=(8,), n_latent_encoder=(4,),
                            n_hidden_decoder=(8,), n_latent_decoder=(784,),
                            k=2, compute_dtype=None, fused_likelihood=False)
    eng = zoo.serving_engine(ecfg, max_batch=2)
    assert eng.k == 2
    x = (np.random.RandomState(5).rand(1, 784) > 0.5).astype(np.float32)
    assert np.isfinite(eng.score(x)).all()


# ---------------------------------------------------------------------------
# the synthetic load sweep (slow profile)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_cli_synthetic_load_sweep(tmp_path):
    """End-to-end ``iwae-serve`` synthetic load: warmup line then a snapshot
    with zero recompiles across the ragged stream and sane latency fields."""
    r = subprocess.run(
        [sys.executable, "-m", "iwae_replication_project_tpu.serving",
         "--preset", "digits-vae-1l-k1", "--ops", "score",
         "--max-batch", "8", "--requests", "24", "--sizes", "1,3,7,2",
         "--timeout-s", "30", "--log-dir", str(tmp_path / "runs"),
         "--metrics-port", "0"],
        capture_output=True, text=True, timeout=600,
        env={**__import__("os").environ, "JAX_PLATFORMS": "cpu",
             "IWAE_COMPILE_CACHE": str(tmp_path / "cache")})
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(ln) for ln in r.stdout.splitlines()
             if ln.startswith("{")]
    warm = next(ln for ln in lines if "warmup" in ln)
    snap = next(ln for ln in lines if "counters" in ln)
    assert warm["warmup"]["programs"] == 4  # score x ladder(1,2,4,8)
    assert warm["metrics_port"] > 0  # the Prometheus endpoint bound a port
    c = snap["counters"]
    assert c["completed"] == c["submitted"] > 0
    assert c["aot_misses"] == 0 and c["recompiles"] == 0
    assert snap["throughput_rows_per_sec"] > 0
    assert any(k.startswith("score/") and v["p99_s"] is not None
               for k, v in snap["latency"].items())
    # the JSONL stamp landed through the shared MetricsLogger pipeline
    jsonl = tmp_path / "runs" / "serving" / "metrics.jsonl"
    assert jsonl.exists()
    row = json.loads(jsonl.read_text().splitlines()[-1])
    assert row["completed"] == c["completed"]


# ---------------------------------------------------------------------------
# the lifted kernel gate (ISSUE 12): probe-gated fused serving + stamps
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiny_logits():
    """A fuse-ELIGIBLE tiny model (the gate requires likelihood='logits';
    the module `tiny` fixture's clamp likelihood pins it to reference)."""
    cfg = model.ModelConfig(x_dim=D, likelihood="logits", **TINY)
    params = model.init_params(jax.random.PRNGKey(0), cfg)
    x = (np.random.RandomState(3).rand(17, D) > 0.5).astype(np.float32)
    return {"cfg": cfg, "params": params, "x": x}


def make_logits_engine(tiny_logits, **kw):
    kw.setdefault("k", 4)
    kw.setdefault("max_batch", 8)
    kw.setdefault("timeout_s", None)
    return ServingEngine(params=tiny_logits["params"],
                         model_config=tiny_logits["cfg"], **kw)


def test_kernel_path_force_validation(tiny_logits):
    with pytest.raises(ValueError, match="kernel_path"):
        make_logits_engine(tiny_logits, kernel_path="mosaic")


def test_unpinned_engine_bitwise_matches_pinned(tiny_logits):
    """THE lift acceptance pin: the unpinned engine (probe-gated auto) and
    every forced fused path return bitwise-identical results to the
    historical pin (kernel_path='reference') on the same ragged stream.
    On this CPU host auto resolves reference (no TPU -> no pallas, tiny
    working set -> no scan), so the auto leg also proves the fallback IS
    the pinned program; the blocked_scan leg proves the FUSED serving
    program against it (the scan forward is bitwise-equal by design)."""
    x = tiny_logits["x"]
    engines = {
        "reference": make_logits_engine(tiny_logits,
                                        kernel_path="reference"),
        "auto": make_logits_engine(tiny_logits),
        "blocked_scan": make_logits_engine(tiny_logits,
                                           kernel_path="blocked_scan"),
    }
    outs = {}
    for name, eng in engines.items():
        got = [eng.score(x[:n]) for n in (1, 3, 7, 2)]
        outs[name] = np.concatenate(got)
    assert np.array_equal(outs["reference"], outs["auto"])
    assert np.array_equal(outs["reference"], outs["blocked_scan"])
    # the stamps tell the three apart (the observable the fleet scrapes)
    assert engines["auto"].metrics.snapshot()["kernel"]["score/b4/k4"][
        "path"] == "reference"
    assert engines["blocked_scan"].metrics.snapshot()["kernel"][
        "score/b4/k4"]["path"] == "blocked_scan"


def test_unpinned_fused_warm_ragged_zero_compiles(tiny_logits):
    """The fused serving engine keeps the warm-path contract: warmup every
    rung, then a ragged stream compiles NOTHING (the gate resolution is
    memoized outside the trace, so probe work cannot leak into dispatch)."""
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    eng = make_logits_engine(tiny_logits, kernel_path="blocked_scan")
    eng.warmup(ops=("score",))
    s0 = cache_stats()
    for n in (1, 3, 7, 2, 8, 5, 1, 4):
        eng.score(tiny_logits["x"][:n])
    d = stats_delta(s0)
    assert d["aot_misses"] == 0, "fused ragged stream compiled after warmup"
    c = eng.metrics.snapshot()["counters"]
    assert c["aot_misses"] == 0 and c["recompiles"] == 0


def test_clamp_model_is_gate_ineligible(tiny):
    """A likelihood='clamp' model cannot fuse (the kernel computes the
    exact logits-form Bernoulli): the gate must resolve reference even
    when the engine asks for a fused path by force."""
    eng = make_engine(tiny, timeout_s=None, kernel_path="blocked_scan")
    cfg_d, path, tile = eng._kernel_for("score", 4, 4)
    assert path == "reference" and tile is None and cfg_d is eng.cfg
    out = eng.score(tiny["x"][:3])
    assert out.shape == (3,) and np.isfinite(out).all()


def test_encode_decode_stay_on_reference(tiny_logits):
    """Only score routes through the decoder block: encode/decode resolve
    reference regardless of forcing (their programs never touch it)."""
    eng = make_logits_engine(tiny_logits, kernel_path="blocked_scan")
    assert eng._kernel_for("encode", 4, 4)[1] == "reference"
    assert eng._kernel_for("decode", 0, 4)[1] == "reference"
    assert eng._kernel_for("score", 4, 4)[1] == "blocked_scan"


def test_kernel_stamp_schema(tiny_logits):
    """The ISSUE 12 metrics satellite: kernel_path (and tile when fused)
    in snapshot/flat and on the Prometheus page, per (op, bucket, k)."""
    from iwae_replication_project_tpu.ops import hot_loop as hl
    from iwae_replication_project_tpu.telemetry.exporters import (
        prometheus_text)

    eng = make_logits_engine(tiny_logits, kernel_path="blocked_scan")
    eng.score(tiny_logits["x"][:3])          # bucket 4
    eng.encode(tiny_logits["x"][:1])         # bucket 1, reference
    snap = eng.metrics.snapshot()
    rec = snap["kernel"]["score/b4/k4"]
    assert rec == {"path_code": hl.PATH_CODES["blocked_scan"],
                   "path": "blocked_scan", "tile": None}
    assert snap["kernel"]["encode/b1/k4"]["path"] == "reference"
    flat = eng.metrics.flat()
    assert flat["kernel/score/b4/k4/path_code"] == float(
        hl.PATH_CODES["blocked_scan"])
    assert all(isinstance(v, float) for v in flat.values())
    page = prometheus_text([eng.metrics.registry])
    assert "kernel_score_b4_k4" in page
    # a forced-pallas engine stamps its tile (interpret mode on CPU: the
    # estimate admits the (tk, 1) row tile without a probe)
    eng_p = make_logits_engine(tiny_logits, kernel_path="pallas")
    cfg_d, path, tile = eng_p._kernel_for("score", 4, 4)
    assert path == "pallas" and tile == (4, 1)
    assert cfg_d.hot_loop_tile == (4, 1)


def test_forced_pallas_serving_matches_reference(tiny_logits):
    """The row-vmapped kernel itself (interpret mode off-TPU) through the
    REAL engine dispatch: numerically equal to the pinned path (the kernel
    reorders the pixel reduction, so this pin is allclose; the bitwise
    pins ride the reference/blocked_scan paths)."""
    x = tiny_logits["x"][:5]
    pinned = make_logits_engine(tiny_logits, kernel_path="reference")
    fused = make_logits_engine(tiny_logits, kernel_path="pallas")
    a, b = pinned.score(x), fused.score(x)
    assert np.allclose(a, b, rtol=1e-5, atol=1e-4)
    assert fused.metrics.snapshot()["kernel"]["score/b8/k4"][
        "path"] == "pallas"
