"""Sharded large-k scoring service tests (ISSUE 9).

Layers, bottom up:

* ``_merge_lse_over_sp`` — the cross-device online-logsumexp merge, unit-
  tested directly under shard_map on the fake-device mesh, including
  ragged final chunks and the all-``-inf`` row edge case;
* the sharded score program — matched-RNG parity with a host-loop
  reference across mesh shapes, ragged k (k not divisible by k_chunk),
  and idle-device block schedules;
* ``ShardedScoreEngine`` — bitwise parity with the offline
  ``parallel/eval.sharded_score_offline`` scorer through the padded
  bucket path, zero recompiles over a ragged (batch, k) stream, and the
  typed out-of-range-k rejection at the engine boundary;
* the replica router — large-k classification onto sharded replicas with
  fake engines, the fleet-wide k bound, and the typed ``bad_request``
  surfaces at the router and over the wire.
"""

import threading
from concurrent.futures import Future

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.ops.logsumexp import (
    OnlineLSE,
    online_logsumexp_init,
    online_logsumexp_merge,
    online_logsumexp_update,
)
from iwae_replication_project_tpu.parallel import make_mesh
from iwae_replication_project_tpu.parallel.eval import (
    _merge_lse_over_sp,
    sharded_score_offline,
)
from iwae_replication_project_tpu.parallel.mesh import AXES, shard_map
from iwae_replication_project_tpu.serving import (
    BucketLadder,
    KChunkMenu,
    ServingEngine,
    ShardedScoreEngine,
)
from iwae_replication_project_tpu.serving.buckets import validate_k

D = 12
CFG = model.ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                        n_hidden_dec=(8, 16), n_latent_dec=(6, 12), x_dim=D)
CHUNK = 4


@pytest.fixture(scope="module")
def tiny():
    params = model.init_params(jax.random.PRNGKey(0), CFG)
    x = (np.random.RandomState(0).rand(9, D) > 0.5).astype(np.float32)
    return {"params": params, "x": x,
            "base_key": jax.device_put(jax.random.PRNGKey(7))}


def make_sharded(tiny, mesh, **kw):
    kw.setdefault("k_chunk", CHUNK)
    kw.setdefault("k_max", 100)
    kw.setdefault("k", 8)
    kw.setdefault("max_batch", 8)
    kw.setdefault("timeout_s", 60.0)
    return ShardedScoreEngine(params=tiny["params"], model_config=CFG,
                              mesh=mesh, **kw)


# ---------------------------------------------------------------------------
# the 2-D (batch_bucket, k) menu + the shared k validator
# ---------------------------------------------------------------------------

def test_k_chunk_menu():
    menu = KChunkMenu(batch=BucketLadder((2, 4, 8)), k_chunk=250, k_max=5000)
    assert menu.validate_k(1) == 1
    assert menu.validate_k(5000) == 5000
    assert menu.n_chunks(250) == 1
    assert menu.n_chunks(251) == 2      # ragged final chunk
    assert menu.n_chunks(5000) == 20
    for bad in (0, -3, 5001):
        with pytest.raises(ValueError, match="out of range"):
            menu.validate_k(bad)
    for bad in ("50", 2.5, True, None):
        with pytest.raises(ValueError, match="integer"):
            menu.validate_k(bad)
    with pytest.raises(ValueError, match="k_chunk"):
        KChunkMenu(batch=BucketLadder((2,)), k_chunk=0)
    with pytest.raises(ValueError, match="k_max"):
        KChunkMenu(batch=BucketLadder((2,)), k_max=0)


def test_validate_k_accepts_numpy_integers():
    assert validate_k(np.int32(7), 10) == 7
    assert isinstance(validate_k(np.int64(7), 10), int)


# ---------------------------------------------------------------------------
# _merge_lse_over_sp: the cross-device merge, in isolation
# ---------------------------------------------------------------------------

def _run_merge(mesh, m, s):
    """Feed per-device partial states ``m, s [sp, B]`` through the real
    merge under shard_map; returns host (m_g, safe, s_g)."""
    def local(m_l, s_l):
        state = OnlineLSE(m=m_l[0], s=s_l[0], n=jnp.int32(0))
        return _merge_lse_over_sp(state)

    fn = jax.jit(shard_map(
        local, mesh=mesh,
        in_specs=(P(AXES.sp), P(AXES.sp)),
        out_specs=(P(), P(), P()),
        check_vma=False))
    return tuple(np.asarray(v) for v in fn(jnp.asarray(m), jnp.asarray(s)))


@pytest.mark.parametrize("sp", [2, 4])
def test_merge_matches_sequential_associative_merge(devices, sp):
    mesh = make_mesh(dp=1, sp=sp)
    rng = np.random.RandomState(3)
    m = rng.randn(sp, 5).astype(np.float32) * 10
    s = rng.rand(sp, 5).astype(np.float32) + 0.1
    m_g, safe, s_g = _run_merge(mesh, m, s)
    want = OnlineLSE(m=jnp.asarray(m[0]), s=jnp.asarray(s[0]),
                     n=jnp.int32(0))
    for i in range(1, sp):
        want = online_logsumexp_merge(
            want, OnlineLSE(m=jnp.asarray(m[i]), s=jnp.asarray(s[i]),
                            n=jnp.int32(0)))
    np.testing.assert_array_equal(m_g, np.asarray(want.m))
    np.testing.assert_allclose(s_g, np.asarray(want.s), rtol=1e-6)
    # the finalized log p̂ the program computes from (safe, s_g)
    np.testing.assert_allclose(
        np.log(s_g) + safe,
        np.asarray(jnp.log(want.s)
                   + jnp.where(jnp.isfinite(want.m), want.m, 0.0)),
        rtol=1e-6)


def test_merge_idle_device_contributes_exact_zero(devices):
    """A device whose blocks were all masked (its whole k range is beyond
    k) carries (m=-inf, s=0) — the merge must treat that as an EXACT zero
    contribution, not a NaN."""
    mesh = make_mesh(dp=1, sp=2)
    m = np.stack([np.array([1.0, -2.0], np.float32),
                  np.full((2,), -np.inf, np.float32)])
    s = np.stack([np.array([0.5, 1.5], np.float32),
                  np.zeros((2,), np.float32)])
    m_g, safe, s_g = _run_merge(mesh, m, s)
    np.testing.assert_array_equal(m_g, m[0])
    np.testing.assert_array_equal(safe, m[0])
    np.testing.assert_array_equal(s_g, s[0])   # bitwise: + 0 exactly


def test_merge_all_devices_all_inf_rows(devices):
    """ALL devices all--inf for a row (no live sample anywhere): the merge
    must produce s_g=0 with a finite 'safe' max, so the finalize yields
    -inf — never NaN (the exp(-inf - -inf) trap)."""
    mesh = make_mesh(dp=1, sp=2)
    m = np.full((2, 3), -np.inf, np.float32)
    s = np.zeros((2, 3), np.float32)
    m_g, safe, s_g = _run_merge(mesh, m, s)
    assert np.all(np.isneginf(m_g))
    np.testing.assert_array_equal(safe, np.zeros(3, np.float32))
    np.testing.assert_array_equal(s_g, np.zeros(3, np.float32))
    with np.errstate(divide="ignore"):
        out = np.log(s_g) + safe   # the program's finalize: log(0) = -inf
    assert np.all(np.isneginf(out)) and not np.any(np.isnan(out))


def test_merge_of_ragged_chunk_states_matches_flat_logsumexp(devices):
    """Per-device carries built from RAGGED chunk splits (different chunk
    boundaries per device) merge to the same logsumexp as one flat pass —
    the associativity the sharded scorer leans on."""
    mesh = make_mesh(dp=1, sp=2)
    rng = np.random.RandomState(5)
    blocks = [rng.randn(n, 4).astype(np.float32)
              for n in (3, 1, 2, 5)]       # ragged chunks
    halves = [blocks[:2], blocks[2:]]
    m, s = [], []
    for chunks in halves:
        st = online_logsumexp_init((4,))
        for c in chunks:
            st = online_logsumexp_update(st, jnp.asarray(c), axis=0)
        m.append(np.asarray(st.m))
        s.append(np.asarray(st.s))
    m_g, safe, s_g = _run_merge(mesh, np.stack(m), np.stack(s))
    flat = np.concatenate(blocks, axis=0)
    want = np.log(np.sum(np.exp(flat - flat.max(0)), axis=0)) + flat.max(0)
    np.testing.assert_allclose(np.log(s_g) + safe, want, rtol=1e-5)


# ---------------------------------------------------------------------------
# the sharded program: matched-RNG reference across mesh shapes + ragged k
# ---------------------------------------------------------------------------

def _served_cfg():
    """The config the engine actually serves (the fused-likelihood pin)."""
    import dataclasses
    return dataclasses.replace(CFG, fused_likelihood=False)


def _reference_scores(tiny, seeds, x, k, chunk=CHUNK):
    """Host-loop twin of the sharded program's RNG/merge contract: per row,
    draw ceil(k/chunk) canonical blocks keyed fold_in(fold_in(base, seed),
    g), mask global sample index >= k to -inf, fold through the online
    carry in block order."""
    cfg = _served_cfg()
    out = []
    n_blocks = -(-k // chunk)
    for seed, row in zip(seeds, x):
        st = online_logsumexp_init((1,))
        for g in range(n_blocks):
            key = jax.random.fold_in(
                jax.random.fold_in(tiny["base_key"], int(seed)), g)
            lw = model.log_weights(tiny["params"], cfg, key, row[None],
                                   chunk)[:, 0]
            idx = g * chunk + np.arange(chunk)
            lw = jnp.where(jnp.asarray(idx) < k, lw, -jnp.inf)
            st = online_logsumexp_update(st, lw[:, None], axis=0)
        safe = jnp.where(jnp.isfinite(st.m), st.m, 0.0)
        out.append(float((jnp.log(st.s) + safe - jnp.log(float(k)))[0]))
    return np.array(out, np.float32)


@pytest.mark.parametrize("k", [1, 3, 8, 10, 17])
@pytest.mark.parametrize("dp,sp", [(1, 1), (2, 2), (1, 4)])
def test_sharded_program_matches_reference(devices, tiny, dp, sp, k):
    """The program == the host-loop matched-RNG reference for every mesh
    shape, including ragged final chunks (k % chunk != 0) and idle devices
    (fewer blocks than sp)."""
    mesh = make_mesh(dp=dp, sp=sp)
    seeds = np.arange(4, dtype=np.int32)
    x = tiny["x"][:4]
    got = np.asarray(sharded_score_offline(
        tiny["params"], _served_cfg(), mesh, tiny["base_key"], seeds, x, k,
        k_chunk=CHUNK))
    want = _reference_scores(tiny, seeds, x, k)
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


def test_sharded_program_k_independent_of_mesh_samples(devices, tiny):
    """RNG is keyed by GLOBAL block index: the same (seed, k, chunk) must
    agree across mesh shapes to float tolerance (the sampled weights are
    bitwise identical; only the merge order differs)."""
    seeds = np.arange(4, dtype=np.int32)
    x = tiny["x"][:4]
    outs = [np.asarray(sharded_score_offline(
        tiny["params"], _served_cfg(), make_mesh(dp=dp, sp=sp),
        tiny["base_key"], seeds, x, 17, k_chunk=CHUNK))
        for dp, sp in ((1, 1), (2, 2), (1, 8))]
    for other in outs[1:]:
        np.testing.assert_allclose(outs[0], other, rtol=1e-6, atol=1e-7)


def test_offline_scorer_pads_ragged_batches(devices, tiny):
    """A batch not divisible by dp pads invisibly (per-row RNG): the 3-row
    result on a dp=2 mesh == the same rows scored in a 4-row batch."""
    mesh = make_mesh(dp=2, sp=2)
    seeds = np.arange(3, dtype=np.int32)
    got = np.asarray(sharded_score_offline(
        tiny["params"], _served_cfg(), mesh, tiny["base_key"], seeds,
        tiny["x"][:3], 10, k_chunk=CHUNK))
    full = np.asarray(sharded_score_offline(
        tiny["params"], _served_cfg(), mesh, tiny["base_key"],
        np.arange(4, dtype=np.int32), tiny["x"][:4], 10, k_chunk=CHUNK))
    np.testing.assert_array_equal(got, full[:3])


# ---------------------------------------------------------------------------
# ShardedScoreEngine: bucket parity, dynamic-k warm path, typed rejection
# ---------------------------------------------------------------------------

def test_sharded_engine_bitwise_parity_with_offline_scorer(devices, tiny):
    """Engine-served ragged batches == the offline parallel/eval scorer at
    the engine's minted seeds, BITWISE — through coalescing, bucket
    padding, and slicing. The serving API is the paper's evaluation."""
    mesh = make_mesh(dp=2, sp=2)
    eng = make_sharded(tiny, mesh)
    seed = 0
    for n, k in ((1, 3), (3, 8), (7, 17), (2, 100)):
        got = eng.score(tiny["x"][:n], k=k)
        off = np.asarray(sharded_score_offline(
            tiny["params"], eng.cfg, mesh, eng._base_key,
            np.arange(seed, seed + n, dtype=np.int32), tiny["x"][:n], k,
            k_chunk=CHUNK))
        seed += n
        assert got.dtype == off.dtype
        assert np.array_equal(np.atleast_1d(got), off), (n, k)


def test_paper_grade_k5000_served_bitwise_equal_to_offline(devices, tiny):
    """THE acceptance pin (ISSUE 9): a k=5000 score request served through
    the engine — production k_chunk=250, so the real 20-block stream —
    returns the bitwise-identical log p̂(x) the offline parallel/eval
    scorer computes, with zero recompiles after warmup."""
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    mesh = make_mesh(dp=1, sp=2)
    eng = make_sharded(tiny, mesh, k_chunk=250, k_max=5000, k=50,
                       max_batch=2)
    eng.warmup()
    s0 = cache_stats()
    got = eng.score(tiny["x"][0], k=5000)
    assert np.isfinite(got)
    d = stats_delta(s0)
    assert d["aot_misses"] == 0 and d["persistent_cache_misses"] == 0
    off = np.asarray(sharded_score_offline(
        tiny["params"], eng.cfg, mesh, eng._base_key,
        np.zeros((1,), np.int32), tiny["x"][0][None], 5000, k_chunk=250))
    assert np.array_equal(np.asarray(got), off[0])


def test_sharded_engine_zero_recompiles_over_ragged_batch_and_k(devices,
                                                               tiny):
    """THE tentpole pin: k is dynamic, so after warmup a ragged stream in
    BOTH batch size and k hits zero AOT misses and zero XLA recompiles."""
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    mesh = make_mesh(dp=2, sp=2)
    eng = make_sharded(tiny, mesh)
    warm = eng.warmup()
    # score + score_adaptive pre-built per rung (targets dynamic too)
    assert warm["programs"] == 2 * len(eng.ladder.buckets)
    s0 = cache_stats()
    futs = []
    for n, k in ((1, 50), (3, 7), (2, 1), (8, 100), (5, 99), (1, 8),
                 (4, 63)):
        futs.extend(eng.submit("score", r, k=k) for r in tiny["x"][:n])
    eng.flush()
    for f in futs:
        assert np.isfinite(f.result(timeout=60))
    d = stats_delta(s0)
    assert d["aot_misses"] == 0, f"ragged (batch, k) stream compiled: {d}"
    c = eng.metrics.snapshot()["counters"]
    assert c["recompiles"] == 0
    assert c["aot_hits"] == c["dispatches"] > 0


def test_sharded_engine_pipelined_matches_inline(devices, tiny):
    """The two-stage pipeline (InflightWindow) dispatches multi-chunk
    programs identically to inline flush: same seeds -> bitwise equal."""
    mesh = make_mesh(dp=1, sp=2)

    def run(start):
        eng = make_sharded(tiny, mesh, max_inflight=2, max_wait_us=200.0)
        if start:
            eng.start()
        try:
            futs = [eng.submit("score", r, k=10) for r in tiny["x"][:5]]
            if not start:
                eng.flush()
            return [np.asarray(f.result(timeout=120)) for f in futs]
        finally:
            if start:
                eng.stop()

    a, b = run(False), run(True)
    for x, y in zip(a, b):
        assert np.array_equal(x, y)


def test_sharded_engine_rejects_out_of_range_k(devices, tiny):
    """The typed bad_request at the engine boundary: ValueError before any
    queueing or program build, for every invalid shape of k."""
    eng = make_sharded(tiny, make_mesh(dp=1, sp=1))
    for bad in (0, -1, 101):
        with pytest.raises(ValueError, match="out of range"):
            eng.submit("score", tiny["x"][0], k=bad)
    for bad in (True, 2.5, "50"):
        with pytest.raises(ValueError, match="integer"):
            eng.submit("score", tiny["x"][0], k=bad)
    with pytest.raises(ValueError, match="unknown op"):
        eng.submit("encode", tiny["x"][0])   # score-only replica
    assert eng.metrics.snapshot()["counters"]["submitted"] == 0


def test_base_engine_rejects_out_of_range_k(tiny):
    """The same contract on the single-device fast path, where an
    unbounded k would otherwise be a silent giant compile."""
    eng = ServingEngine(params=tiny["params"], model_config=CFG, k=4,
                        k_max=16, max_batch=4)
    with pytest.raises(ValueError, match="out of range"):
        eng.submit("score", tiny["x"][0], k=17)
    with pytest.raises(ValueError, match="integer"):
        eng.submit("score", tiny["x"][0], k="many")
    assert np.isfinite(eng.score(tiny["x"][0], k=16))   # the bound serves


def test_base_engine_rejects_k_max_below_default_k(tiny):
    """An explicit bound below the engine's own default k fails at
    CONSTRUCTION — not at every later default-k submit."""
    with pytest.raises(ValueError, match="below this engine's default"):
        ServingEngine(params=tiny["params"], model_config=CFG, k=32,
                      k_max=16, max_batch=4)


def test_sharded_engine_requires_dp_aligned_buckets(devices, tiny):
    with pytest.raises(ValueError, match="multiples of dp"):
        ShardedScoreEngine(params=tiny["params"], model_config=CFG,
                           mesh=make_mesh(dp=2, sp=1),
                           ladder=BucketLadder((1, 2, 4)))


def test_sharded_engine_default_k_must_fit_menu(devices, tiny):
    with pytest.raises(ValueError, match="out of range"):
        make_sharded(tiny, make_mesh(dp=1, sp=1), k=512, k_max=100)
    # an INHERITED default (k unset: the base engine's 50) clamps to the
    # menu; only an explicit out-of-menu k is a construction error
    eng = ShardedScoreEngine(params=tiny["params"], model_config=CFG,
                             mesh=make_mesh(dp=1, sp=1), k_chunk=4,
                             k_max=10)
    assert eng.k == 10


# ---------------------------------------------------------------------------
# router classification (fake engines — no device)
# ---------------------------------------------------------------------------

class FakeReplica:
    """Minimal engine surface with capability attributes."""

    def __init__(self, *, sharded=False, k_max=16, ops=("score", "encode",
                                                        "decode"), dims=4):
        self.sharded = sharded
        self.k_max = k_max
        self.k = 5
        self.row_dims = {op: dims for op in ops}
        self.served = []
        self.lock = threading.Lock()

    def submit(self, op, row, k=None, *, seed=None):
        with self.lock:
            self.served.append((op, k, seed))
        f = Future()
        f.set_result(float(seed if seed is not None else -1))
        return f

    def start(self):
        pass

    def stop(self, timeout_s=None):
        pass

    def warmup(self, ops=(), ks=None):
        return {}


def _mixed_router(**kw):
    from iwae_replication_project_tpu.serving.frontend import ReplicaRouter

    fast = FakeReplica(sharded=False, k_max=16)
    big = FakeReplica(sharded=True, k_max=5000, ops=("score",))
    return fast, big, ReplicaRouter([fast, big], **kw)


def test_router_classifies_large_k_onto_sharded_replica():
    fast, big, router = _mixed_router()
    assert router.large_k_threshold == 16   # auto: the fast replica's k_max
    assert router.k_max == 5000
    router.submit("score", [0.0] * 4, k=4).result(timeout=5)
    router.submit("score", [0.0] * 4, k=5000).result(timeout=5)
    router.submit("score", [0.0] * 4).result(timeout=5)   # default k: fast
    assert [op for op, _, _ in fast.served] == ["score", "score"]
    assert [(op, k) for op, k, _ in big.served] == [("score", 5000)]


def test_router_keeps_non_score_ops_off_sharded_replicas():
    fast, big, router = _mixed_router()
    router.submit("encode", [0.0] * 4, k=5).result(timeout=5)
    router.submit("decode", [0.0] * 4).result(timeout=5)
    assert big.served == []
    assert len(fast.served) == 2


def test_router_rejects_out_of_range_k_synchronously():
    fast, big, router = _mixed_router()
    for bad in (0, 5001):
        with pytest.raises(ValueError, match="out of range"):
            router.submit("score", [0.0] * 4, k=bad)
    with pytest.raises(ValueError, match="integer"):
        router.submit("score", [0.0] * 4, k=True)
    assert router.outstanding == 0          # nothing leaked past rejection
    assert fast.served == [] and big.served == []


def test_router_explicit_threshold_overrides_auto():
    fast, big, router = _mixed_router(large_k_threshold=8)
    router.submit("score", [0.0] * 4, k=9).result(timeout=5)
    assert [(op, k) for op, k, _ in big.served] == [("score", 9)]
    assert fast.served == []


def test_router_all_sharded_fleet_serves_small_k():
    from iwae_replication_project_tpu.serving.frontend import ReplicaRouter

    big = FakeReplica(sharded=True, k_max=5000, ops=("score",))
    router = ReplicaRouter([big])
    assert router.large_k_threshold is None
    router.submit("score", [0.0] * 4, k=2).result(timeout=5)
    assert [(op, k) for op, k, _ in big.served] == [("score", 2)]


def test_router_unbounded_fast_replicas_disable_classification():
    """Fast replicas without a k_max (RemoteEngine proxies, fakes): the
    auto threshold must fall back to NO classification — a 0 threshold
    would starve the fast path of every explicit-k request."""
    from iwae_replication_project_tpu.serving.frontend import ReplicaRouter

    fast = FakeReplica(sharded=False, k_max=None)
    big = FakeReplica(sharded=True, k_max=5000, ops=("score",))
    router = ReplicaRouter([fast, big])
    assert router.large_k_threshold is None
    router.submit("score", [0.0] * 4, k=5).result(timeout=5)
    assert len(fast.served) == 1 and big.served == []


def test_router_homogeneous_fast_fleet_keeps_old_behavior():
    from iwae_replication_project_tpu.serving.frontend import ReplicaRouter

    fasts = [FakeReplica(k_max=16) for _ in range(2)]
    router = ReplicaRouter(fasts)
    assert router.large_k_threshold is None
    router.submit("score", [0.0] * 4, k=16).result(timeout=5)
    with pytest.raises(ValueError, match="out of range"):
        router.submit("score", [0.0] * 4, k=17)


def test_router_large_k_with_sharded_replica_down_is_unavailable():
    """k above the threshold with the only sharded replica unhealthy must
    read as fleet-state (unavailable), not as a bad request."""
    from iwae_replication_project_tpu.serving.frontend import (
        ReplicaRouter, ReplicaUnavailable)

    fast = FakeReplica(sharded=False, k_max=16)
    big = FakeReplica(sharded=True, k_max=5000, ops=("score",))
    router = ReplicaRouter([fast, big])
    router._replicas[1].healthy = False
    with pytest.raises(ReplicaUnavailable):
        router.submit("score", [0.0] * 4, k=100)
    # the fast path keeps serving small k
    router.submit("score", [0.0] * 4, k=4).result(timeout=5)
    assert len(fast.served) == 1


# ---------------------------------------------------------------------------
# the wire surface: typed bad_request for out-of-range k over TCP
# ---------------------------------------------------------------------------

def test_tier_typed_bad_request_for_k_over_the_wire():
    from iwae_replication_project_tpu.serving.frontend import (
        ServingTier, TierClient)
    from iwae_replication_project_tpu.serving.frontend.client import (
        TierError)

    fast = FakeReplica(sharded=False, k_max=16)
    big = FakeReplica(sharded=True, k_max=5000, ops=("score",))
    tier = ServingTier([fast, big], monitor_interval_s=60.0).start()
    try:
        cli = TierClient("127.0.0.1", tier.port)
        info = cli.info()
        assert info["k_max"] == 5000
        assert info["large_k_threshold"] == 16
        assert info["sharded_replicas"] == 1
        assert set(info["ops"]) == {"score", "encode", "decode"}
        # buckets/k describe the FAST class even when replica order puts
        # the sharded engine first; the sharded class gets its own sub-doc
        # (None here: fakes carry no menu)
        assert info["sharded"] is None
        # valid large k routes; every invalid k is a typed bad_request
        # RESPONSE on a live connection
        assert cli.score([0.0] * 4, k=100) is not None
        for bad in (0, -1, 5001, True, 2.5, "many"):
            with pytest.raises(TierError) as ei:
                cli.score([0.0] * 4, k=bad)
            assert ei.value.code == "bad_request", bad
        # the connection survived all six rejections
        assert cli.score([0.0] * 4, k=3) is not None
        cli.close()
    finally:
        tier.stop()


def test_mixed_tier_info_describes_both_classes(devices, tiny):
    """Real mixed fleet: info() reports the fast ladder at the top level
    and the sharded class's menu in its own sub-doc, whatever the replica
    order."""
    from iwae_replication_project_tpu.serving.frontend import ServingTier

    fast = ServingEngine(params=tiny["params"], model_config=CFG, k=4,
                         k_max=16, max_batch=4)
    big = make_sharded(tiny, make_mesh(dp=2, sp=1), max_batch=8)
    tier = ServingTier([big, fast], monitor_interval_s=60.0)
    try:
        info = tier.info()
        assert info["buckets"] == list(fast.ladder.buckets)
        assert info["k"] == 4
        assert info["sharded"] == {"buckets": [2, 4, 8], "k_chunk": CHUNK,
                                   "k_max": 100, "k": 8}
    finally:
        tier.router.drain(timeout_s=5.0)


def test_cli_k_split_refuses_threshold_at_or_above_k_max():
    """--k-threshold >= --k-max with sharded replicas would make them
    unreachable; the CLI refuses instead of wiring a dead class."""
    from iwae_replication_project_tpu.serving.cli import (
        _k_split, build_argparser)

    args = build_argparser().parse_args(
        ["--sharded-replicas", "1", "--k-max", "500",
         "--k-threshold", "500"])
    with pytest.raises(SystemExit, match="k-threshold"):
        _k_split(args)
    # coherent default split: threshold < k_max, both classes reachable
    args = build_argparser().parse_args(
        ["--sharded-replicas", "1", "--k-max", "500"])
    fast_k_max, threshold = _k_split(args)
    assert fast_k_max == threshold == 250
    # explicit threshold above the engine default still tiles [1, k_max]
    args = build_argparser().parse_args(
        ["--sharded-replicas", "1", "--k-max", "5000",
         "--k-threshold", "2000"])
    assert _k_split(args) == (2000, 2000)


def test_tier_routes_mixed_traffic_to_the_right_class():
    from iwae_replication_project_tpu.serving.frontend import (
        ServingTier, TierClient)

    fast = FakeReplica(sharded=False, k_max=16)
    big = FakeReplica(sharded=True, k_max=5000, ops=("score",))
    tier = ServingTier([fast, big], monitor_interval_s=60.0).start()
    try:
        cli = TierClient("127.0.0.1", tier.port)
        cli.score([0.0] * 4, k=4)
        cli.score([0.0] * 4, k=500)
        cli.encode([0.0] * 4)
        cli.close()
    finally:
        tier.stop()
    assert [(op, k) for op, k, _ in big.served] == [("score", 500)]
    assert sorted(op for op, _, _ in fast.served) == ["encode", "score"]


# ---------------------------------------------------------------------------
# the lifted kernel gate on the sharded scorer (ISSUE 12)
# ---------------------------------------------------------------------------

CFG_LOGITS = model.ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(6, 3),
                               n_hidden_dec=(8, 16), n_latent_dec=(6, 12),
                               x_dim=D, likelihood="logits")


@pytest.fixture(scope="module")
def tiny_logits():
    params = model.init_params(jax.random.PRNGKey(0), CFG_LOGITS)
    x = (np.random.RandomState(2).rand(9, D) > 0.5).astype(np.float32)
    return {"params": params, "x": x}


def make_sharded_logits(tiny_logits, mesh, **kw):
    kw.setdefault("k_chunk", CHUNK)
    kw.setdefault("k_max", 100)
    kw.setdefault("k", 8)
    kw.setdefault("max_batch", 8)
    kw.setdefault("timeout_s", None)
    return ShardedScoreEngine(params=tiny_logits["params"],
                              model_config=CFG_LOGITS, mesh=mesh, **kw)


def test_sharded_unpinned_bitwise_matches_pinned(tiny_logits):
    """ISSUE 12 acceptance for the sharded scorer: the probe-gated engine
    and the forced fused (blocked_scan) engine are request-by-request
    bitwise identical to the historical pin over a ragged (batch, k)
    stream. The gate runs at the k_chunk block shape — the dynamic k never
    enters resolution, so one executable per bucket still serves every k."""
    mesh = make_mesh(dp=1, sp=1, devices=jax.devices()[:1])
    x = tiny_logits["x"]
    outs = {}
    engines = {}
    for name in ("reference", "auto", "blocked_scan"):
        eng = make_sharded_logits(
            tiny_logits, mesh,
            kernel_path=None if name == "auto" else name)
        engines[name] = eng
        fs = [eng.submit("score", r, k=kk)
              for kk in (3, 8, 17) for r in x[:4]]
        eng.flush()
        outs[name] = np.asarray([f.result() for f in fs])
    assert np.array_equal(outs["reference"], outs["auto"])
    assert np.array_equal(outs["reference"], outs["blocked_scan"])
    # the dynamic-k program stamps ONE slot per bucket (kdyn), not per k
    snap = engines["blocked_scan"].metrics.snapshot()["kernel"]
    assert snap["score/b4/kdyn"]["path"] == "blocked_scan"
    assert not any("/k3" in key or "/k17" in key for key in snap)


def test_sharded_fused_zero_recompiles_ragged_k(tiny_logits):
    """The zero-recompile contract survives the lift: the FUSED sharded
    engine warms one executable per bucket and a ragged (batch, k) stream
    compiles nothing (gate resolution is bucket-only by construction)."""
    from iwae_replication_project_tpu.utils.compile_cache import (
        cache_stats, stats_delta)

    mesh = make_mesh(dp=1, sp=1, devices=jax.devices()[:1])
    eng = make_sharded_logits(tiny_logits, mesh,
                              kernel_path="blocked_scan")
    eng.warmup()
    s0 = cache_stats()
    fs = [eng.submit("score", r, k=kk)
          for kk in (1, 5, 9, 33, 100) for r in tiny_logits["x"][:3]]
    eng.flush()
    for f in fs:
        f.result()
    d = stats_delta(s0)
    assert d["aot_misses"] == 0, "ragged (batch, k) stream recompiled"
    assert d["persistent_cache_misses"] == 0


def test_sharded_fused_offline_parity(tiny_logits):
    """Engine-vs-offline bitwise parity holds for the fused program too:
    the offline scorer called with the engine's DISPATCH config runs the
    identical jitted program (parity by construction, as in PR 9)."""
    mesh = make_mesh(dp=1, sp=1, devices=jax.devices()[:1])
    eng = make_sharded_logits(tiny_logits, mesh,
                              kernel_path="blocked_scan")
    x = tiny_logits["x"][0]
    seed = eng._seed_counter
    got = eng.score(x, k=17)
    cfg_d, path, _ = eng._kernel_for("score", 17, eng.ladder.bucket_for(1))
    assert path == "blocked_scan"
    off = np.asarray(sharded_score_offline(
        tiny_logits["params"], cfg_d, mesh, eng._base_key,
        np.array([seed], np.int32), x[None], 17, k_chunk=CHUNK))[0]
    assert np.array_equal(np.asarray(got), off)
