"""Telemetry layer tests: registry, spans, exporters, on-device diagnostics,
and the driver integration (the digits smoke of the acceptance criteria).
"""

import json
import os
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.telemetry import (
    MetricRegistry,
    current_span,
    get_registry,
    prometheus_text,
    span,
    start_metrics_server,
)
from iwae_replication_project_tpu.telemetry.diagnostics import (
    DiagnosticsConfig,
    ess,
    estimator_diagnostics,
    weight_diagnostics,
)

CFG = model.ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(8, 4),
                        n_hidden_dec=(8, 16), n_latent_dec=(8, 784))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(2)
        reg.gauge("g").set(7.5)
        for v in (0.01, 0.02, 0.04):
            reg.histogram("h").record(v)
        snap = reg.snapshot()
        assert snap["counters"]["c"] == 3
        assert snap["gauges"]["g"] == 7.5
        s = snap["histograms"]["h"]
        assert s["count"] == 3
        assert s["mean"] == pytest.approx(0.07 / 3)
        assert s["p50"] is not None and s["p99"] >= s["p50"]

    def test_get_or_create_returns_same_instrument(self):
        reg = MetricRegistry()
        assert reg.counter("x") is reg.counter("x")
        assert reg.histogram("h") is reg.histogram("h")

    def test_type_conflict_rejected(self):
        reg = MetricRegistry()
        reg.counter("name")
        with pytest.raises(ValueError, match="different instrument type"):
            reg.gauge("name")

    def test_rows_flat_and_numeric(self):
        reg = MetricRegistry()
        reg.counter("c").inc(5)
        reg.gauge("g").set(1.0)
        reg.histogram("lat/h").record(0.01)
        rows = reg.rows(prefix="p/")
        assert rows["p/c"] == 5.0
        assert rows["p/lat/h/count"] == 1.0
        assert all(isinstance(v, float) for v in rows.values())

    def test_empty_histogram_percentile_none(self):
        reg = MetricRegistry()
        h = reg.histogram("h")
        assert h.percentile(0.5) is None
        assert h.summary()["p99"] is None
        assert "h/p99" not in reg.rows()  # None stats dropped from rows

    def test_thread_safety_counts_every_increment(self):
        reg = MetricRegistry()

        def work():
            for _ in range(1000):
                reg.counter("n").inc()
                reg.histogram("h").record(0.001)

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counter("n").value == 4000
        assert reg.histogram("h").summary()["count"] == 4000


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class TestSpans:
    def test_nesting_builds_paths(self):
        reg = MetricRegistry()
        with span("a", registry=reg) as outer:
            assert outer == "a" == current_span()
            with span("b/c", registry=reg) as inner:
                assert inner == "a/b/c" == current_span()
        assert current_span() is None
        rows = reg.rows()
        assert rows["span/a/count"] == 1.0
        assert rows["span/a/b/c/count"] == 1.0
        # parent wall time includes the child's
        assert reg.histogram("span/a").total >= \
            reg.histogram("span/a/b/c").total

    def test_exception_still_records_and_unwinds(self):
        reg = MetricRegistry()
        with pytest.raises(RuntimeError):
            with span("boom", registry=reg):
                raise RuntimeError("x")
        assert current_span() is None
        assert reg.histogram("span/boom").summary()["count"] == 1

    def test_default_registry_is_process_wide(self):
        with span("telemetry-test/default"):
            pass
        assert get_registry().histogram(
            "span/telemetry-test/default").summary()["count"] >= 1

    def test_thread_local_stacks_do_not_interleave(self):
        reg = MetricRegistry()
        seen = {}

        def work(name):
            with span(name, registry=reg):
                seen[name] = current_span()

        threads = [threading.Thread(target=work, args=(f"t{i}",))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert seen == {"t0": "t0", "t1": "t1", "t2": "t2"}


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

class TestExporters:
    def test_prometheus_text_shapes(self):
        reg = MetricRegistry()
        reg.counter("submitted").inc(3)
        reg.gauge("queue_depth").set(2)
        for v in (0.001, 0.002, 0.004):
            reg.histogram("latency/score/b4").record(v)
        page = prometheus_text(reg)
        assert "# TYPE iwae_submitted_total counter" in page
        assert "iwae_submitted_total 3" in page
        assert "iwae_queue_depth 2" in page
        assert 'iwae_latency_score_b4{quantile="0.99"}' in page
        assert "iwae_latency_score_b4_count 3" in page
        assert "iwae_latency_score_b4_sum" in page

    def test_prometheus_merges_registries(self):
        a, b = MetricRegistry(), MetricRegistry()
        a.counter("only_a").inc()
        b.counter("only_b").inc()
        page = prometheus_text((a, b))
        assert "iwae_only_a_total 1" in page and "iwae_only_b_total 1" in page

    def test_http_metrics_endpoint(self):
        reg = MetricRegistry()
        reg.counter("hits").inc(9)
        srv = start_metrics_server(reg, port=0)
        try:
            port = srv.server_address[1]
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
            assert "iwae_hits_total 9" in body
            reg.counter("hits").inc()  # a later scrape sees fresh values
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10).read().decode()
            assert "iwae_hits_total 10" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=10)
        finally:
            srv.shutdown()

    def test_metrics_server_shutdown_releases_port(self):
        """shutdown() must close the listening socket too — otherwise a
        restart on the same fixed --metrics-port gets EADDRINUSE."""
        reg = MetricRegistry()
        srv = start_metrics_server(reg, port=0)
        port = srv.server_address[1]
        srv.shutdown()
        srv2 = start_metrics_server(reg, port=port)  # rebind the same port
        try:
            assert srv2.server_address[1] == port
        finally:
            srv2.shutdown()

    def test_serving_metrics_rides_the_registry(self):
        """ServingMetrics is an adapter over MetricRegistry — its counters
        and histograms must be visible to the Prometheus exporter without
        any serving-specific code."""
        from iwae_replication_project_tpu.serving.metrics import ServingMetrics
        m = ServingMetrics()
        m.count("submitted", 4)
        m.record_latency("score", 4, 0.005)
        page = prometheus_text(m.registry)
        assert "iwae_submitted_total 4" in page
        assert 'iwae_latency_score_b4{quantile="0.5"}' in page

    def test_serving_pipeline_metrics_export(self):
        """The pipelined-dispatch instruments — the inflight gauge and the
        queue-wait / device-wait latency split — ride the same registry and
        reach every export surface: Prometheus text (the CLI's /metrics
        endpoint serves exactly this page) and the MetricsLogger flat rows.
        Schema pinned here and in tests/test_serving.py."""
        from iwae_replication_project_tpu.serving.metrics import ServingMetrics
        m = ServingMetrics()
        m.set_inflight(2)
        m.record_queue_wait("score", 4, 0.002)
        m.record_device_wait("score", 4, 0.009)
        page = prometheus_text(m.registry)
        assert "# TYPE iwae_inflight gauge" in page
        assert "iwae_inflight 2" in page
        assert 'iwae_queue_wait_score_b4{quantile="0.5"}' in page
        assert 'iwae_device_wait_score_b4{quantile="0.5"}' in page
        flat = m.flat()
        assert flat["inflight"] == 2.0
        assert flat["queue_wait/score/b4/count"] == 1.0
        assert flat["device_wait/score/b4/count"] == 1.0

    def test_executable_store_schema(self):
        """The multi-tenant executable store's telemetry contract (ISSUE
        13): ``store/{hits,misses,evictions,demotions,readmits}`` counters
        and the ``store/resident_bytes``-vs-budget gauges land on the
        PROCESS registry (the Prometheus page every ``iwae-serve
        --metrics-port`` run exports), and every ServingMetrics
        snapshot/flat carries the same numbers under ``store``."""
        import jax.numpy as jnp

        from iwae_replication_project_tpu.serving.metrics import (
            ServingMetrics)
        from iwae_replication_project_tpu.telemetry.registry import (
            get_registry)
        from iwae_replication_project_tpu.utils import compile_cache as cc

        @jax.jit
        def probe(x):
            return (x + 1.0).sum()

        with cc.isolated_aot_registry(budget_bytes=None):
            s0 = cc.cache_stats()
            cc.aot_call("telemetry_probe", probe, (jnp.ones((4, 4)),),
                        model="pin-model")
            cc.aot_call("telemetry_probe", probe, (jnp.ones((4, 4)),),
                        model="pin-model")
            d = cc.stats_delta(s0)
            assert d["store_misses"] == 1 and d["store_hits"] == 1
            # process-registry surface (Prometheus page)
            page = prometheus_text(get_registry())
            assert "iwae_store_misses_total" in page
            assert "iwae_store_hits_total" in page
            assert "# TYPE iwae_store_resident_bytes gauge" in page
            # ServingMetrics surface: snapshot["store"] + flat store/ keys
            m = ServingMetrics()
            snap = m.snapshot()
            for key in ("hits", "misses", "evictions", "demotions",
                        "readmits", "resident_bytes", "budget_bytes",
                        "entries", "per_model"):
                assert key in snap["store"], key
            assert snap["store"]["entries"] == 1
            assert "pin-model" in snap["store"]["per_model"]
            flat = m.flat()
            for key in ("store/hits", "store/misses", "store/evictions",
                        "store/demotions", "store/readmits",
                        "store/resident_bytes", "store/entries"):
                assert isinstance(flat[key], float), key
            assert "store/budget_bytes" not in flat   # unbounded: omitted

    def test_model_labeled_latency_schema(self):
        """A model-labeled engine's histograms carry the tenant in the key
        on every surface — ``latency/<model>/<op>/b<n>`` flat/snapshot and
        the Prometheus spelling — while the unlabeled schema is untouched
        (pinned in test_serving.py)."""
        from iwae_replication_project_tpu.serving.metrics import (
            ServingMetrics)

        m = ServingMetrics(model="zoo-x")
        m.record_latency("score", 4, 0.004)
        m.record_queue_wait("score", 4, 0.001)
        snap = m.snapshot()
        assert snap["model"] == "zoo-x"
        assert "zoo-x/score/b4" in snap["latency"]
        assert "zoo-x/score/b4" in snap["queue_wait"]
        flat = m.flat()
        assert flat["latency/zoo-x/score/b4/count"] == 1.0
        page = prometheus_text(m.registry)
        assert 'iwae_latency_zoo_x_score_b4{quantile="0.5"}' in page


# ---------------------------------------------------------------------------
# on-device diagnostics
# ---------------------------------------------------------------------------

class TestWeightDiagnostics:
    def test_ess_uniform_weights_is_k(self, rng):
        assert np.allclose(np.asarray(ess(jnp.zeros((8, 5)))), 8.0)

    def test_ess_degenerate_weights_is_one(self):
        lw = jnp.concatenate([jnp.full((1, 5), 60.0), jnp.zeros((7, 5))])
        assert np.allclose(np.asarray(ess(lw)), 1.0, atol=1e-3)

    def test_ess_shift_invariant(self, rng):
        """ESS depends on the normalized weights only — adding a constant to
        all log-weights (the max-stabilization the bound applies) must not
        change it."""
        lw = jax.random.normal(rng, (16, 6))
        np.testing.assert_allclose(np.asarray(ess(lw)),
                                   np.asarray(ess(lw + 123.0)), rtol=1e-5)

    def test_ess_matches_direct_formula(self, rng):
        lw = np.asarray(jax.random.normal(rng, (32, 4)), np.float64)
        w = np.exp(lw - lw.max(0))
        direct = w.sum(0) ** 2 / (w ** 2).sum(0)
        np.testing.assert_allclose(np.asarray(ess(jnp.asarray(lw))), direct,
                                   rtol=1e-4)

    def test_weight_diagnostics_bundle(self, rng):
        lw = jax.random.normal(rng, (8, 5)) * 2.0
        d = weight_diagnostics(lw)
        assert d["diag/ess_frac"] == pytest.approx(
            float(d["diag/ess"]) / 8, rel=1e-6)
        assert d["diag/log_weight_var"] == pytest.approx(
            float(jnp.mean(jnp.var(lw, axis=0))), rel=1e-5)

    def test_snr_window_validated(self):
        """window < 1 would divide zero moments by zero -> silent NaN
        diag/grad_snr* rows; it must refuse at construction."""
        with pytest.raises(ValueError, match="snr_window"):
            DiagnosticsConfig(snr_window=0)
        with pytest.raises(ValueError, match="snr_window"):
            from iwae_replication_project_tpu.utils.config import (
                ExperimentConfig)
            ExperimentConfig(snr_window=-1).diagnostics_config()

    def test_estimator_diagnostics_program(self, rng):
        params = model.init_params(rng, CFG)
        batches = jnp.asarray(
            (np.random.RandomState(0).rand(3, 8, 784) > 0.5)
            .astype(np.float32))
        out = estimator_diagnostics(params, CFG, jax.random.fold_in(rng, 1),
                                    batches, 6, DiagnosticsConfig())
        vals = {k: float(v) for k, v in out.items()}
        assert set(vals) == {"diag/ess", "diag/ess_frac",
                             "diag/log_weight_var", "diag/kl_q_p",
                             "diag/active_units", "diag/active_frac"}
        assert 1.0 <= vals["diag/ess"] <= 6.0
        assert 0.0 <= vals["diag/active_units"] <= sum(CFG.n_latent_enc)
        assert vals["diag/active_frac"] == pytest.approx(
            vals["diag/active_units"] / sum(CFG.n_latent_enc))
        assert all(np.isfinite(v) for v in vals.values())


class TestEpochDiagnostics:
    def _setup(self):
        from iwae_replication_project_tpu.training import create_train_state
        spec = ObjectiveSpec("IWAE", k=4)
        state = create_train_state(jax.random.PRNGKey(0), CFG)
        x = jnp.asarray((np.random.RandomState(0).rand(64, 784) > 0.5)
                        .astype(np.float32))
        return spec, state, x

    def test_on_off_trainstate_bit_identical(self):
        """Diagnostics observe; they must not perturb. Same key, same data:
        params, opt state and losses agree bitwise between modes."""
        from iwae_replication_project_tpu.training.epoch import make_epoch_fn
        spec, state, x = self._setup()
        off = make_epoch_fn(spec, CFG, 64, 16, donate=False)
        on = make_epoch_fn(spec, CFG, 64, 16, donate=False,
                           diagnostics=DiagnosticsConfig(snr_window=2))
        s_off, losses_off = off(state, x)
        s_on, (losses_on, diag) = on(state, x)
        np.testing.assert_array_equal(np.asarray(losses_off),
                                      np.asarray(losses_on))
        for a, b in zip(jax.tree.leaves(s_off.params),
                        jax.tree.leaves(s_on.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for k in ("diag/grad_snr", "diag/grad_snr_enc", "diag/grad_snr_dec"):
            v = float(diag[k])
            assert np.isfinite(v) and v > 0, (k, v)

    def test_disabled_config_equals_none(self):
        """DiagnosticsConfig(enabled=False) must take the byte-identical
        no-diagnostics path: plain (state, losses) return shape."""
        from iwae_replication_project_tpu.training.epoch import make_epoch_fn
        spec, state, x = self._setup()
        fn = make_epoch_fn(spec, CFG, 64, 16, donate=False,
                           diagnostics=DiagnosticsConfig(enabled=False))
        s, losses = fn(state, x)
        assert losses.shape == (4,)

    def test_block_mode_reports_last_epoch(self):
        from iwae_replication_project_tpu.training.epoch import make_epoch_fn
        spec, state, x = self._setup()
        single = make_epoch_fn(spec, CFG, 64, 16, donate=False,
                               diagnostics=DiagnosticsConfig(snr_window=2))
        block = make_epoch_fn(spec, CFG, 64, 16, donate=False,
                              diagnostics=DiagnosticsConfig(snr_window=2),
                              epochs_per_call=3)
        s1, (l1, d1) = single(state, x)
        s2, (l2, d2) = single(s1, x)
        s3, (l3, d3) = single(s2, x)
        sb, (lb, db) = block(state, x)
        np.testing.assert_array_equal(
            np.asarray(lb),
            np.concatenate([np.asarray(l) for l in (l1, l2, l3)]))
        for k in d3:
            assert float(db[k]) == pytest.approx(float(d3[k]), rel=1e-5), k

    def test_parallel_epoch_diagnostics_replicated(self, devices):
        from iwae_replication_project_tpu.parallel import make_mesh
        from iwae_replication_project_tpu.parallel.dp import (
            make_parallel_epoch_fn, replicate)
        from iwae_replication_project_tpu.training import create_train_state
        spec = ObjectiveSpec("IWAE", k=4)
        mesh = make_mesh(dp=4, sp=2)
        state = create_train_state(jax.random.PRNGKey(0), CFG)
        x = jnp.asarray((np.random.RandomState(0).rand(64, 784) > 0.5)
                        .astype(np.float32))
        fn = make_parallel_epoch_fn(
            spec, CFG, mesh, 64, 16, donate=False,
            diagnostics=DiagnosticsConfig(snr_window=2))
        state_r, (losses, diag) = fn(replicate(mesh, state),
                                     replicate(mesh, x))
        assert losses.shape == (4,)
        for k, v in diag.items():
            assert np.isfinite(float(v)) and float(v) > 0, k


# ---------------------------------------------------------------------------
# driver integration: the digits smoke of the acceptance criteria
# ---------------------------------------------------------------------------

class TestDriverIntegration:
    DIAG_KEYS = ("diag/ess", "diag/ess_frac", "diag/log_weight_var",
                 "diag/kl_q_p", "diag/active_units", "diag/grad_snr",
                 "diag/grad_snr_enc", "diag/grad_snr_dec")

    def _cfg(self, tmp_path, **over):
        from iwae_replication_project_tpu.utils.config import ExperimentConfig
        d = dict(dataset="digits", data_dir=str(tmp_path / "data"),
                 n_hidden_encoder=(16,), n_hidden_decoder=(16,),
                 n_latent_encoder=(4,), n_latent_decoder=(784,),
                 loss_function="IWAE", k=4, batch_size=32, n_stages=2,
                 eval_k=4, nll_k=8, nll_chunk=4, eval_batch_size=16,
                 activity_samples=8, save_figures=False,
                 log_dir=str(tmp_path / "runs"),
                 checkpoint_dir=str(tmp_path / "ckpt"))
        d.update(over)
        return ExperimentConfig(**d)

    def test_digits_smoke_emits_diagnostics_per_eval(self, tmp_path):
        """Acceptance: a digits smoke run emits ESS, log-weight variance and
        gradient SNR per eval into metrics.jsonl (and TensorBoard), with the
        span/registry telemetry in its own runs/<run>/telemetry stream."""
        from iwae_replication_project_tpu.experiment import run_experiment
        from tests.test_logging import decode_tfevents

        cfg = self._cfg(tmp_path)
        _, history = run_experiment(cfg, max_batches_per_pass=2,
                                    eval_subset=32)
        run_dir = os.path.join(cfg.log_dir, cfg.run_name())
        rows = [json.loads(ln) for ln in open(
            os.path.join(run_dir, "metrics.jsonl"))]
        assert [r["stage"] for r in rows] == [1, 2]  # one row per eval, only
        for row in rows:
            for key in self.DIAG_KEYS:
                assert key in row and np.isfinite(row[key]), key
            assert 1.0 <= row["diag/ess"] <= cfg.eval_k
        # the same tags reached TensorBoard
        (events_file,) = [f for f in os.listdir(run_dir)
                          if f.startswith("events.out.tfevents.")]
        tags = {v["tag"] for ev in decode_tfevents(
            os.path.join(run_dir, events_file))[1:] for v in ev["values"]}
        assert set(self.DIAG_KEYS) <= tags
        # span telemetry landed in the side stream, not metrics.jsonl
        trows = [json.loads(ln) for ln in open(
            os.path.join(run_dir, "telemetry", "metrics.jsonl"))]
        assert len(trows) == 2
        assert any(k.startswith("span/train/stage") for k in trows[-1])
        assert any(k.startswith("span/eval/") for k in trows[-1])
        # ... and the history the caller gets carries the same scalars
        assert all(k in history[-1][0] for k in self.DIAG_KEYS)

    def test_no_diagnostics_restores_pre_telemetry_stream(self, tmp_path):
        from iwae_replication_project_tpu.experiment import run_experiment

        cfg = self._cfg(tmp_path, diagnostics=False, n_stages=1)
        _, history = run_experiment(cfg, max_batches_per_pass=2,
                                    eval_subset=32)
        run_dir = os.path.join(cfg.log_dir, cfg.run_name())
        row = json.loads(open(os.path.join(
            run_dir, "metrics.jsonl")).read().strip().splitlines()[-1])
        assert not any(k.startswith("diag/") for k in row)
        assert not os.path.exists(os.path.join(run_dir, "telemetry"))

    def test_cli_flags(self):
        from iwae_replication_project_tpu.utils.config import config_from_args
        assert config_from_args([]).diagnostics is True
        assert config_from_args(["--no-diagnostics"]).diagnostics is False
        assert config_from_args(["--snr-window", "7"]).snr_window == 7
