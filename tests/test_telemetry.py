"""Telemetry layer tests: registry, spans, exporters, on-device diagnostics,
and the driver integration (the digits smoke of the acceptance criteria).
"""

import json
import os
import threading
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import iwae as model
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.telemetry import (
    MetricRegistry,
    current_span,
    get_registry,
    prometheus_text,
    span,
    start_metrics_server,
)
from iwae_replication_project_tpu.telemetry.diagnostics import (
    DiagnosticsConfig,
    ess,
    estimator_diagnostics,
    weight_diagnostics,
)

CFG = model.ModelConfig(n_hidden_enc=(16, 8), n_latent_enc=(8, 4),
                        n_hidden_dec=(8, 16), n_latent_dec=(8, 784))


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = MetricRegistry()
        reg.counter("c").inc()
        reg.counter("c").inc(2)
        reg.gauge("g").set(7.5)
        for v in (0.01, 0.02, 0.04):
            reg.histogram("h").record(v)
        snap = reg.snapshot()
        assert snap["counters"]["c"] == 3
        assert snap["gauges"]["g"] == 7.5
        s = snap["histograms"]["h"]
        assert s["count"] == 3
        assert s["mean"] == pytest.approx(0.07 / 3)
        assert s["p50"] is not None and s["p99"] >= s["p50"]

    def test_get_or_create_returns_same_instrument(self):
        reg = MetricRegistry()
        assert reg.counter("x") is reg.counter("x")
        assert reg.histogram("h") is reg.histogram("h")

    def test_type_conflict_rejected(self):
        reg = MetricRegistry()
        reg.counter("name")
        with pytest.raises(ValueError, match="different instrument type"):
            reg.gauge("name")

    def test_rows_flat_and_numeric(self):
        reg = MetricRegistry()
        reg.counter("c").inc(5)
        reg.gauge("g").set(1.0)
        reg.histogram("lat/h").record(0.01)
        rows = reg.rows(prefix="p/")
        assert rows["p/c"] == 5.0
        assert rows["p/lat/h/count"] == 1.0
        assert all(isinstance(v, float) for v in rows.values())

    def test_empty_histogram_percentile_none(self):
        reg = MetricRegistry()
        h = reg.histogram("h")
        assert h.percentile(0.5) is None
        assert h.summary()["p99"] is None
        assert "h/p99" not in reg.rows()  # None stats dropped from rows

    def test_thread_safety_counts_every_increment(self):
        reg = MetricRegistry()

        def work():
            for _ in range(1000):
                reg.counter("n").inc()
                reg.histogram("h").record(0.001)

        threads = [threading.Thread(target=work) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert reg.counter("n").value == 4000
        assert reg.histogram("h").summary()["count"] == 4000


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------

class TestSpans:
    def test_nesting_builds_paths(self):
        reg = MetricRegistry()
        with span("a", registry=reg) as outer:
            assert outer == "a" == current_span()
            with span("b/c", registry=reg) as inner:
                assert inner == "a/b/c" == current_span()
        assert current_span() is None
        rows = reg.rows()
        assert rows["span/a/count"] == 1.0
        assert rows["span/a/b/c/count"] == 1.0
        # parent wall time includes the child's
        assert reg.histogram("span/a").total >= \
            reg.histogram("span/a/b/c").total

    def test_exception_still_records_and_unwinds(self):
        reg = MetricRegistry()
        with pytest.raises(RuntimeError):
            with span("boom", registry=reg):
                raise RuntimeError("x")
        assert current_span() is None
        assert reg.histogram("span/boom").summary()["count"] == 1

    def test_default_registry_is_process_wide(self):
        with span("telemetry-test/default"):
            pass
        assert get_registry().histogram(
            "span/telemetry-test/default").summary()["count"] >= 1

    def test_thread_local_stacks_do_not_interleave(self):
        reg = MetricRegistry()
        seen = {}

        def work(name):
            with span(name, registry=reg):
                seen[name] = current_span()

        threads = [threading.Thread(target=work, args=(f"t{i}",))
                   for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert seen == {"t0": "t0", "t1": "t1", "t2": "t2"}


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

class TestExporters:
    def test_prometheus_text_shapes(self):
        reg = MetricRegistry()
        reg.counter("submitted").inc(3)
        reg.gauge("queue_depth").set(2)
        for v in (0.001, 0.002, 0.004):
            reg.histogram("latency/score/b4").record(v)
        page = prometheus_text(reg)
        assert "# TYPE iwae_submitted_total counter" in page
        assert "iwae_submitted_total 3" in page
        assert "iwae_queue_depth 2" in page
        assert 'iwae_latency_score_b4{quantile="0.99"}' in page
        assert "iwae_latency_score_b4_count 3" in page
        assert "iwae_latency_score_b4_sum" in page

    def test_prometheus_help_lines(self):
        """Every exported family carries a # HELP line before its # TYPE
        (satellite: today only # TYPE) — known prefixes get real prose,
        anything else a generic line naming the original path."""
        reg = MetricRegistry()
        reg.counter("submitted").inc()
        reg.gauge("slo/score/latency_burn_5m").set(0.5)
        reg.histogram("latency/score/b4").record(0.001)
        page = prometheus_text(reg).splitlines()
        for metric in ("iwae_submitted_total", "iwae_slo_score_latency_burn_5m",
                       "iwae_latency_score_b4"):
            (help_i,) = [i for i, ln in enumerate(page)
                         if ln.startswith(f"# HELP {metric} ")]
            assert page[help_i + 1].startswith(f"# TYPE {metric} ")
            assert len(page[help_i].split(" ", 3)[3]) > 0  # non-empty text
        # a # HELP for every # TYPE, pairwise
        types = [ln.split()[2] for ln in page if ln.startswith("# TYPE")]
        helps = [ln.split()[2] for ln in page if ln.startswith("# HELP")]
        assert types == helps

    def test_prometheus_sum_is_tracked_total(self):
        """Histogram `_sum` comes from the Histogram's exact running
        `total`, not a mean*count reconstruction (satellite)."""
        reg = MetricRegistry()
        h = reg.histogram("latency/score/b4")
        for v in (0.1, 0.1, 0.1):
            h.record(v)
        page = prometheus_text(reg)
        assert f"iwae_latency_score_b4_sum {h.total!r}" in page
        # the summary document itself now carries the total verbatim
        assert h.summary()["total"] == h.total

    def test_prometheus_collisions_counted(self):
        """Same-name instruments across merged registries stay
        last-writer-wins (documented merge order) but are COUNTED on the
        process registry's telemetry/export_collisions counter instead of
        passing silently (satellite)."""
        c0 = get_registry().counter("telemetry/export_collisions").value
        a, b = MetricRegistry(), MetricRegistry()
        a.counter("dup").inc(1)
        b.counter("dup").inc(5)
        a.gauge("g_dup").set(1)
        b.gauge("g_dup").set(2)
        page = prometheus_text((a, b))
        assert "iwae_dup_total 5" in page          # last writer still wins
        assert "iwae_g_dup 2" in page
        assert get_registry().counter(
            "telemetry/export_collisions").value == c0 + 2
        # no collisions -> no increment
        prometheus_text((MetricRegistry(), MetricRegistry()))
        assert get_registry().counter(
            "telemetry/export_collisions").value == c0 + 2

    def test_prometheus_merges_registries(self):
        a, b = MetricRegistry(), MetricRegistry()
        a.counter("only_a").inc()
        b.counter("only_b").inc()
        page = prometheus_text((a, b))
        assert "iwae_only_a_total 1" in page and "iwae_only_b_total 1" in page

    def test_http_metrics_endpoint(self):
        reg = MetricRegistry()
        reg.counter("hits").inc(9)
        srv = start_metrics_server(reg, port=0)
        try:
            port = srv.server_address[1]
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
            assert "iwae_hits_total 9" in body
            reg.counter("hits").inc()  # a later scrape sees fresh values
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/", timeout=10).read().decode()
            assert "iwae_hits_total 10" in body
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/nope", timeout=10)
        finally:
            srv.shutdown()

    def test_metrics_server_shutdown_releases_port(self):
        """shutdown() must close the listening socket too — otherwise a
        restart on the same fixed --metrics-port gets EADDRINUSE."""
        reg = MetricRegistry()
        srv = start_metrics_server(reg, port=0)
        port = srv.server_address[1]
        srv.shutdown()
        srv2 = start_metrics_server(reg, port=port)  # rebind the same port
        try:
            assert srv2.server_address[1] == port
        finally:
            srv2.shutdown()

    def test_serving_metrics_rides_the_registry(self):
        """ServingMetrics is an adapter over MetricRegistry — its counters
        and histograms must be visible to the Prometheus exporter without
        any serving-specific code."""
        from iwae_replication_project_tpu.serving.metrics import ServingMetrics
        m = ServingMetrics()
        m.count("submitted", 4)
        m.record_latency("score", 4, 0.005)
        page = prometheus_text(m.registry)
        assert "iwae_submitted_total 4" in page
        assert 'iwae_latency_score_b4{quantile="0.5"}' in page

    def test_serving_pipeline_metrics_export(self):
        """The pipelined-dispatch instruments — the inflight gauge and the
        queue-wait / device-wait latency split — ride the same registry and
        reach every export surface: Prometheus text (the CLI's /metrics
        endpoint serves exactly this page) and the MetricsLogger flat rows.
        Schema pinned here and in tests/test_serving.py."""
        from iwae_replication_project_tpu.serving.metrics import ServingMetrics
        m = ServingMetrics()
        m.set_inflight(2)
        m.record_queue_wait("score", 4, 0.002)
        m.record_device_wait("score", 4, 0.009)
        page = prometheus_text(m.registry)
        assert "# TYPE iwae_inflight gauge" in page
        assert "iwae_inflight 2" in page
        assert 'iwae_queue_wait_score_b4{quantile="0.5"}' in page
        assert 'iwae_device_wait_score_b4{quantile="0.5"}' in page
        flat = m.flat()
        assert flat["inflight"] == 2.0
        assert flat["queue_wait/score/b4/count"] == 1.0
        assert flat["device_wait/score/b4/count"] == 1.0

    def test_executable_store_schema(self):
        """The multi-tenant executable store's telemetry contract (ISSUE
        13): ``store/{hits,misses,evictions,demotions,readmits}`` counters
        and the ``store/resident_bytes``-vs-budget gauges land on the
        PROCESS registry (the Prometheus page every ``iwae-serve
        --metrics-port`` run exports), and every ServingMetrics
        snapshot/flat carries the same numbers under ``store``."""
        import jax.numpy as jnp

        from iwae_replication_project_tpu.serving.metrics import (
            ServingMetrics)
        from iwae_replication_project_tpu.telemetry.registry import (
            get_registry)
        from iwae_replication_project_tpu.utils import compile_cache as cc

        @jax.jit
        def probe(x):
            return (x + 1.0).sum()

        with cc.isolated_aot_registry(budget_bytes=None):
            s0 = cc.cache_stats()
            cc.aot_call("telemetry_probe", probe, (jnp.ones((4, 4)),),
                        model="pin-model")
            cc.aot_call("telemetry_probe", probe, (jnp.ones((4, 4)),),
                        model="pin-model")
            d = cc.stats_delta(s0)
            assert d["store_misses"] == 1 and d["store_hits"] == 1
            # process-registry surface (Prometheus page)
            page = prometheus_text(get_registry())
            assert "iwae_store_misses_total" in page
            assert "iwae_store_hits_total" in page
            assert "# TYPE iwae_store_resident_bytes gauge" in page
            # ServingMetrics surface: snapshot["store"] + flat store/ keys
            m = ServingMetrics()
            snap = m.snapshot()
            for key in ("hits", "misses", "evictions", "demotions",
                        "readmits", "resident_bytes", "budget_bytes",
                        "entries", "per_model"):
                assert key in snap["store"], key
            assert snap["store"]["entries"] == 1
            assert "pin-model" in snap["store"]["per_model"]
            flat = m.flat()
            for key in ("store/hits", "store/misses", "store/evictions",
                        "store/demotions", "store/readmits",
                        "store/resident_bytes", "store/entries"):
                assert isinstance(flat[key], float), key
            assert "store/budget_bytes" not in flat   # unbounded: omitted

    def test_model_labeled_latency_schema(self):
        """A model-labeled engine's histograms carry the tenant in the key
        on every surface — ``latency/<model>/<op>/b<n>`` flat/snapshot and
        the Prometheus spelling — while the unlabeled schema is untouched
        (pinned in test_serving.py)."""
        from iwae_replication_project_tpu.serving.metrics import (
            ServingMetrics)

        m = ServingMetrics(model="zoo-x")
        m.record_latency("score", 4, 0.004)
        m.record_queue_wait("score", 4, 0.001)
        snap = m.snapshot()
        assert snap["model"] == "zoo-x"
        assert "zoo-x/score/b4" in snap["latency"]
        assert "zoo-x/score/b4" in snap["queue_wait"]
        flat = m.flat()
        assert flat["latency/zoo-x/score/b4/count"] == 1.0
        page = prometheus_text(m.registry)
        assert 'iwae_latency_zoo_x_score_b4{quantile="0.5"}' in page

    def test_precision_labeled_schema(self):
        """Under a serving precision policy (ISSUE 16) every metric
        surface grows the precision dimension — ``<model>@<precision>``
        histogram labels matching the engine's store label, a
        ``/<precision>``-suffixed kernel stamp key carrying a
        ``precision`` field, a ``precision`` snapshot key, and the
        Prometheus spelling — while ``precision=None`` keeps the schema
        byte-identical to a pre-precision fleet."""
        from iwae_replication_project_tpu.serving.metrics import (
            ServingMetrics)

        m = ServingMetrics(model="zoo-x", precision="bf16")
        m.record_latency("score", 4, 0.004)
        m.set_kernel("score", 3, 4, 1, "fused", None)
        snap = m.snapshot()
        assert snap["precision"] == "bf16"
        assert "zoo-x@bf16/score/b4" in snap["latency"]
        assert snap["kernel"]["score/b4/k3/bf16"]["precision"] == "bf16"
        assert m.flat()["latency/zoo-x@bf16/score/b4/count"] == 1.0
        page = prometheus_text(m.registry)
        assert 'iwae_latency_zoo_x_bf16_score_b4{quantile="0.5"}' in page

        # the fp32-only contract: no policy -> no "precision" key, the
        # historical kernel key, the historical latency label
        base = ServingMetrics(model="zoo-x")
        base.record_latency("score", 4, 0.004)
        base.set_kernel("score", 3, 4, 1, "fused", None)
        bsnap = base.snapshot()
        assert "precision" not in bsnap
        assert "zoo-x/score/b4" in bsnap["latency"]
        assert "score/b4/k3" in bsnap["kernel"]
        assert "precision" not in bsnap["kernel"]["score/b4/k3"]
        assert sorted(bsnap) == sorted(set(snap) - {"precision"})


# ---------------------------------------------------------------------------
# request tracing: context, flight recorder, wire round-trip
# ---------------------------------------------------------------------------

from iwae_replication_project_tpu.telemetry.tracing import (  # noqa: E402
    FlightRecorder,
    chrome_trace_events,
    emit_span,
    parse_wire_trace,
    start_span,
)


class TestTraceContext:
    def test_parse_wire_trace(self):
        assert parse_wire_trace("abc123") == ("abc123", None)
        assert parse_wire_trace("abc/def-1") == ("abc", "def-1")

    @pytest.mark.parametrize("bad", [
        123, {"id": "x"}, ["x"], True,          # non-strings
        "", "a/b/c", "bad trace!", "x/",        # grammar violations
        "a" * 130,                              # oversized
    ])
    def test_parse_wire_trace_rejects(self, bad):
        with pytest.raises(ValueError, match="'trace'"):
            parse_wire_trace(bad)

    def test_span_tree_assembles_on_all_spans_closed(self):
        rec = FlightRecorder(sample_every=1)
        root = start_span("client/request", recorder=rec)
        child = root.child("tier/request", attrs={"op": "score"})
        emit_span(child.ctx(), "engine/queue", 1.0, 2.0)
        child.finish()
        assert rec.traces() == []       # root still open: not finalized
        root.finish()
        (doc,) = rec.traces()
        assert doc["trace_id"] == root.trace_id
        assert doc["root"] == "client/request"
        names = {s["name"]: s for s in doc["spans"]}
        assert set(names) == {"client/request", "tier/request",
                              "engine/queue"}
        ids = {s["span_id"] for s in doc["spans"]}
        assert names["tier/request"]["parent_id"] in ids
        assert names["engine/queue"]["parent_id"] in ids
        assert names["tier/request"]["attrs"] == {"op": "score"}

    def test_wire_context_round_trip_joins_tree(self):
        """A span started from a parsed wire context lands in the SAME
        trace as the minting side (the fleet-of-fleets hop contract)."""
        rec = FlightRecorder(sample_every=1)
        hop = start_span("remote/hop", recorder=rec)
        tid, parent = parse_wire_trace(hop.ctx().wire())
        child = start_span("tier/request", recorder=rec, trace_id=tid,
                           parent_id=parent)
        child.finish()
        hop.finish()
        (doc,) = rec.traces()
        assert len(doc["spans"]) == 2
        assert doc["spans"][-1]["parent_id"] == hop.span_id \
            or doc["spans"][0]["parent_id"] == hop.span_id

    def test_finish_is_idempotent(self):
        rec = FlightRecorder(sample_every=1)
        s = start_span("a", recorder=rec)
        s.finish()
        s.finish(error="late")          # second close: dropped
        (doc,) = rec.traces()
        assert len(doc["spans"]) == 1 and doc["error"] is False


class TestFlightRecorder:
    def _one_trace(self, rec, error=None, duration=0.0):
        s = start_span("r", recorder=rec, t_start=100.0)
        s.finish(error=error, t_end=100.0 + duration)
        return s.trace_id

    def test_schema_pins(self):
        """The retained trace document and stats schemas other tools
        (iwae-trace, the traces wire op, the smoke) consume."""
        rec = FlightRecorder(sample_every=1)
        root = start_span("client/request", recorder=rec)
        root.child("tier/request").finish(error="timeout")
        root.finish()
        (doc,) = rec.traces()
        assert set(doc) == {"trace_id", "root", "duration_s", "error",
                            "kept", "spans"}
        assert doc["error"] is True and doc["kept"] == "error"
        for s in doc["spans"]:
            assert set(s) == {"span_id", "parent_id", "name", "t_start_s",
                              "duration_s", "attrs", "error"}
        stats = rec.stats()
        for key in ("traces_started", "finalized", "kept_error",
                    "kept_slow", "kept_sampled", "dropped", "late_spans",
                    "open_overflow", "abandoned", "retained", "open",
                    "capacity", "sample_every", "slow_fraction"):
            assert key in stats, key

    def test_tail_sampling_keeps_errors_and_one_in_n(self):
        rec = FlightRecorder(sample_every=10, slow_min_history=10 ** 6)
        for i in range(40):
            self._one_trace(rec, error="internal" if i == 17 else None)
        kept = {d["kept"] for d in rec.traces()}
        stats = rec.stats()
        assert stats["kept_error"] == 1
        assert stats["kept_sampled"] == 4       # 1-in-10 of 40
        assert stats["dropped"] == 40 - 5
        assert kept == {"error", "sampled"}

    def test_tail_sampling_keeps_slow_tail(self):
        rec = FlightRecorder(sample_every=10 ** 6, slow_min_history=20,
                             slow_fraction=0.10)
        for _ in range(30):
            self._one_trace(rec, duration=0.01)
        assert rec.stats()["kept_slow"] == 0
        slow_tid = self._one_trace(rec, duration=5.0)
        assert [d["trace_id"] for d in rec.traces()
                if d["kept"] == "slow"] == [slow_tid]

    def test_ring_capacity_bound(self):
        rec = FlightRecorder(capacity=4, sample_every=1)
        tids = [self._one_trace(rec) for _ in range(10)]
        docs = rec.traces()
        assert len(docs) == 4
        assert [d["trace_id"] for d in docs] == tids[-4:]
        assert rec.traces(limit=2) == docs[-2:]
        # limit=0 = NO bodies (the iwae-trace --stats query), not the
        # whole ring via a docs[-0:] slice
        assert rec.traces(limit=0) == []
        assert [d["trace_id"] for d in rec.traces(trace_id=tids[-1])] == \
            [tids[-1]]

    def test_late_spans_counted_not_leaked(self):
        rec = FlightRecorder(sample_every=1)
        s = start_span("r", recorder=rec)
        ctx = s.ctx()
        s.finish()
        emit_span(ctx, "late", 0.0, 1.0)        # trace already finalized
        assert rec.stats()["late_spans"] == 1
        (doc,) = rec.traces()
        assert len(doc["spans"]) == 1

    def test_open_overflow_bounded(self):
        rec = FlightRecorder(sample_every=1, max_open=2, open_ttl_s=10 ** 6)
        spans = [start_span(f"s{i}", recorder=rec) for i in range(5)]
        assert rec.stats()["open"] == 2
        assert rec.stats()["open_overflow"] == 3
        for s in spans:
            s.finish()

    def test_chrome_trace_events_valid(self):
        import json as _json
        rec = FlightRecorder(sample_every=1)
        root = start_span("client/request", recorder=rec)
        root.child("tier/request", attrs={"op": "score"}).finish()
        root.finish(error="timeout")
        doc = chrome_trace_events(rec.traces())
        _json.loads(_json.dumps(doc))           # valid JSON end to end
        xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
        assert len(xs) == 2
        for e in xs:
            assert e["ts"] >= 0 and e["dur"] >= 0 and e["tid"] == 1
            assert "trace_id" in e["args"] and "span_id" in e["args"]
        assert any(e["args"].get("error") == "timeout" for e in xs)

    def test_latency_exemplars_link_quantiles_to_traces(self):
        """Satellite of the tentpole: the serving latency histograms carry
        trace-id exemplars, so a quantile readout names a real trace."""
        from iwae_replication_project_tpu.serving.metrics import (
            ServingMetrics)
        m = ServingMetrics()
        for i in range(20):
            m.record_latency("score", 4, 0.001 * (i + 1),
                             trace_id=f"tid-{i}")
        m.record_latency("encode", 4, 0.001)    # no exemplar: absent below
        snap = m.snapshot()
        ex = snap["latency_exemplars"]
        assert set(ex) == {"score/b4"}
        assert ex["score/b4"]["p99"] == "tid-19"
        assert ex["score/b4"]["p50"] is not None
        h = m.registry.histogram("latency/score/b4")
        near = h.exemplar_near(0.99)
        assert near == {"value": 0.020, "label": "tid-19"}


class _TraceFakeEngine:
    """Trace-blind fake (no ``traces`` attr): the router must keep the
    trace kwarg away from it while still recording its attempt spans."""

    row_dims = {"score": 4}
    k = 5

    def submit(self, op, row, k=None, *, seed=None):
        from concurrent.futures import Future
        f = Future()
        f.set_result(float(seed))
        return f

    def start(self):
        pass

    def stop(self, timeout_s=60.0):
        pass

    def warmup(self, ops=(), ks=None):
        return {}


class TestTraceWire:
    """Trace-context wire round-trip over a real socket (satellite)."""

    @pytest.fixture()
    def tier(self):
        from iwae_replication_project_tpu.serving.frontend import ServingTier
        rec = FlightRecorder(sample_every=1)
        t = ServingTier([_TraceFakeEngine()], port=0, recorder=rec)
        t.start()
        yield t, rec
        t.stop(timeout_s=10)

    def _client(self, tier, **kw):
        from iwae_replication_project_tpu.serving.frontend import TierClient
        return TierClient("127.0.0.1", tier.port, **kw)

    def test_accepted_trace_joins_and_survives(self, tier):
        t, rec = tier
        with self._client(t) as cli:
            rid = cli._next_id = cli._next_id + 1
            import json as _json
            cli._sock.sendall((_json.dumps(
                {"id": rid, "op": "score", "x": [0.0] * 4,
                 "trace": "cafe1234/parent-1"}) + "\n").encode())
            assert cli.wait(rid) == [0.0]
        docs = rec.traces(trace_id="cafe1234")
        deadline = __import__("time").monotonic() + 5.0
        while not docs and __import__("time").monotonic() < deadline:
            docs = rec.traces(trace_id="cafe1234")
        (doc,) = docs
        names = {s["name"] for s in doc["spans"]}
        assert {"tier/request", "tier/admit", "router/attempt-1"} <= names
        tier_span = next(s for s in doc["spans"]
                         if s["name"] == "tier/request")
        # the wire parent id is preserved even though that span lives in
        # another process's recorder
        assert tier_span["parent_id"] == "parent-1"

    @pytest.mark.parametrize("bad", [
        {"not": "a string"}, 123, ["x"],
        "way/too/many/parts", "bad chars!", "x" * 200,
    ])
    def test_malformed_trace_is_typed_bad_request(self, tier, bad):
        import json as _json

        from iwae_replication_project_tpu.serving.frontend.client import (
            TierError)
        t, rec = tier
        with self._client(t) as cli:
            cli._next_id += 1
            rid = cli._next_id
            cli._sock.sendall((_json.dumps(
                {"id": rid, "op": "score", "x": [0.0] * 4,
                 "trace": bad}) + "\n").encode())
            with pytest.raises(TierError) as ei:
                cli.wait(rid)
            assert ei.value.code == "bad_request"
            assert "trace" in str(ei.value)
            # the connection SURVIVES the rejection, and the rejected
            # request consumed no admission-order seed (result = seed 0)
            assert cli.score([0.0] * 4) == [0.0]
        # the malformed request recorded no trace
        assert all(d["root"] != "tier/request" or not d["error"]
                   for d in rec.traces())

    def test_minted_trace_and_traces_op(self, tier):
        t, rec = tier
        with self._client(t) as cli:
            assert cli.score([0.0] * 4) == [0.0]    # tier mints the trace
            raw = cli.traces()
            assert raw["stats"]["retained"] >= 1
            (doc,) = raw["traces"][-1:]
            assert doc["root"] == "tier/request"    # no client span: tier
            chrome = cli.traces(fmt="chrome")       # is the local root
            assert any(e.get("ph") == "X" for e in chrome["traceEvents"])

    def test_disconnect_closes_orphaned_client_spans(self, tier):
        """A dropped connection's unanswered pipelined requests must close
        their auto-minted root spans errored NOW — not linger open until
        the recorder's abandon TTL (and the id->span map must not grow
        across reconnects)."""
        import time as _time
        t, rec = tier
        cli = self._client(t, trace=True, recorder=rec)
        cli.submit("score", [0.0] * 4)
        assert len(cli._spans) == 1
        cli.close()             # response never read
        assert cli._spans == {}
        deadline = _time.monotonic() + 5.0
        doc = None
        while doc is None and _time.monotonic() < deadline:
            for d in rec.traces():
                client_spans = [s for s in d["spans"]
                                if s["name"] == "client/request"]
                if client_spans and client_spans[0]["error"] == "connection":
                    doc = d
            _time.sleep(0.01)
        assert doc is not None, \
            f"orphaned client span never closed: {rec.stats()}"
        assert doc["kept"] == "error"

    def test_tracing_off_still_validates_and_answers_empty(self):
        from iwae_replication_project_tpu.serving.frontend import ServingTier
        from iwae_replication_project_tpu.serving.frontend.client import (
            TierError)
        t = ServingTier([_TraceFakeEngine()], port=0, tracing=False)
        t.start()
        try:
            with self._client(t) as cli:
                import json as _json
                cli._next_id += 1
                rid = cli._next_id
                cli._sock.sendall((_json.dumps(
                    {"id": rid, "op": "score", "x": [0.0] * 4,
                     "trace": 42}) + "\n").encode())
                with pytest.raises(TierError) as ei:
                    cli.wait(rid)
                assert ei.value.code == "bad_request"
                assert cli.score([0.0] * 4) == [0.0]
                doc = cli.traces()
                assert doc == {"stats": None, "traces": []}
        finally:
            t.stop(timeout_s=10)


# ---------------------------------------------------------------------------
# SLO burn-rate monitor
# ---------------------------------------------------------------------------

from iwae_replication_project_tpu.telemetry.slo import (  # noqa: E402
    SLOMonitor,
    SLOObjective,
)


class TestSLO:
    def test_objective_validation(self):
        with pytest.raises(ValueError, match="latency_s"):
            SLOObjective(latency_s=0)
        with pytest.raises(ValueError, match="latency_target"):
            SLOObjective(latency_target=1.0)

    def test_burn_rate_math(self):
        """burn = violation fraction / (1 - target), per window."""
        clock = [1000.0]
        reg = MetricRegistry()
        mon = SLOMonitor(registry=reg,
                         default=SLOObjective(latency_s=0.1,
                                              latency_target=0.9,
                                              availability_target=0.99),
                         clock=lambda: clock[0])
        for _ in range(8):
            mon.observe("score", 0.01)              # good
        mon.observe("score", 0.5)                   # latency violation
        mon.observe("score", 0.01, error_code="internal")   # error (both)
        snap = mon.snapshot()["score"]["windows"]["5m"]
        assert snap["requests"] == 10
        # 2/10 latency-bad over a 0.10 budget -> burn 2.0
        assert snap["latency_burn"] == pytest.approx(2.0)
        # 1/10 errors over a 0.01 budget -> burn 10.0
        assert snap["availability_burn"] == pytest.approx(10.0)
        # gauges carry the same numbers (the Prometheus surface)
        assert reg.gauge("slo/score/latency_burn_5m").value == \
            pytest.approx(2.0)
        # the 1h window saw the same 10 observations -> same burn
        assert reg.gauge("slo/score/availability_burn_1h").value == \
            pytest.approx(10.0)
        assert reg.counter("slo/score/requests").value == 10
        assert reg.counter("slo/score/latency_violations").value == 2
        assert reg.counter("slo/score/errors").value == 1

    def test_windows_rotate_with_the_clock(self):
        clock = [0.0]
        mon = SLOMonitor(registry=MetricRegistry(),
                         windows=((30.0, "30s"),), buckets_per_window=3,
                         clock=lambda: clock[0])
        mon.observe("score", 9.0)                   # violation at t=0
        assert mon.snapshot()["score"]["windows"]["30s"]["requests"] == 1
        clock[0] = 31.0                             # a full window later
        mon.observe("score", 0.0)
        w = mon.snapshot()["score"]["windows"]["30s"]
        assert w["requests"] == 1                   # old bucket expired
        assert w["latency_burn"] == 0.0

    def test_client_faults_never_burn(self):
        mon = SLOMonitor(registry=MetricRegistry())
        mon.observe("score", 0.001, error_code="quota_exceeded")
        w = mon.snapshot()["score"]["windows"]["5m"]
        assert w["availability_burn"] == 0.0

    def test_model_labeled_keys_and_objective_lookup(self):
        reg = MetricRegistry()
        mon = SLOMonitor(
            registry=reg,
            objectives={("zoo-a", "score"): SLOObjective(latency_s=9.0)})
        assert mon.objective_for("zoo-a", "score").latency_s == 9.0
        assert mon.objective_for("zoo-b", "score") is mon.default
        mon.observe("score", 0.001, model="zoo-a")
        assert "zoo-a/score" in mon.snapshot()
        assert "iwae_slo_zoo_a_score_latency_burn_5m" in \
            prometheus_text(reg)

    def test_tier_publishes_slo_schema(self):
        """The serving tier's default monitor: burn gauges appear on the
        tier registry (= the fleet Prometheus page) after traffic, and
        bad_request traffic never mints a key (schema pin)."""
        from iwae_replication_project_tpu.serving.frontend import (
            ServingTier, TierClient)
        from iwae_replication_project_tpu.serving.frontend.client import (
            TierError)
        t = ServingTier([_TraceFakeEngine()], port=0, tracing=False)
        t.start()
        try:
            with TierClient("127.0.0.1", t.port) as cli:
                cli.score([0.0] * 4)
                with pytest.raises(TierError):
                    cli.request("nonsense-op", [0.0] * 4)
        finally:
            t.stop(timeout_s=10)
        page = prometheus_text(t.registry)
        for needle in ("iwae_slo_score_latency_burn_5m",
                       "iwae_slo_score_latency_burn_1h",
                       "iwae_slo_score_availability_burn_5m",
                       "iwae_slo_score_availability_burn_1h",
                       "iwae_slo_score_requests_total"):
            assert needle in page, needle
        assert "nonsense" not in page
        assert set(t.slo.snapshot()) == {"score"}


# ---------------------------------------------------------------------------
# on-device diagnostics
# ---------------------------------------------------------------------------

class TestWeightDiagnostics:
    def test_ess_uniform_weights_is_k(self, rng):
        assert np.allclose(np.asarray(ess(jnp.zeros((8, 5)))), 8.0)

    def test_ess_degenerate_weights_is_one(self):
        lw = jnp.concatenate([jnp.full((1, 5), 60.0), jnp.zeros((7, 5))])
        assert np.allclose(np.asarray(ess(lw)), 1.0, atol=1e-3)

    def test_ess_shift_invariant(self, rng):
        """ESS depends on the normalized weights only — adding a constant to
        all log-weights (the max-stabilization the bound applies) must not
        change it."""
        lw = jax.random.normal(rng, (16, 6))
        np.testing.assert_allclose(np.asarray(ess(lw)),
                                   np.asarray(ess(lw + 123.0)), rtol=1e-5)

    def test_ess_matches_direct_formula(self, rng):
        lw = np.asarray(jax.random.normal(rng, (32, 4)), np.float64)
        w = np.exp(lw - lw.max(0))
        direct = w.sum(0) ** 2 / (w ** 2).sum(0)
        np.testing.assert_allclose(np.asarray(ess(jnp.asarray(lw))), direct,
                                   rtol=1e-4)

    def test_weight_diagnostics_bundle(self, rng):
        lw = jax.random.normal(rng, (8, 5)) * 2.0
        d = weight_diagnostics(lw)
        assert d["diag/ess_frac"] == pytest.approx(
            float(d["diag/ess"]) / 8, rel=1e-6)
        assert d["diag/log_weight_var"] == pytest.approx(
            float(jnp.mean(jnp.var(lw, axis=0))), rel=1e-5)

    def test_snr_window_validated(self):
        """window < 1 would divide zero moments by zero -> silent NaN
        diag/grad_snr* rows; it must refuse at construction."""
        with pytest.raises(ValueError, match="snr_window"):
            DiagnosticsConfig(snr_window=0)
        with pytest.raises(ValueError, match="snr_window"):
            from iwae_replication_project_tpu.utils.config import (
                ExperimentConfig)
            ExperimentConfig(snr_window=-1).diagnostics_config()

    def test_estimator_diagnostics_program(self, rng):
        params = model.init_params(rng, CFG)
        batches = jnp.asarray(
            (np.random.RandomState(0).rand(3, 8, 784) > 0.5)
            .astype(np.float32))
        out = estimator_diagnostics(params, CFG, jax.random.fold_in(rng, 1),
                                    batches, 6, DiagnosticsConfig())
        vals = {k: float(v) for k, v in out.items()}
        assert set(vals) == {"diag/ess", "diag/ess_frac",
                             "diag/log_weight_var", "diag/kl_q_p",
                             "diag/active_units", "diag/active_frac"}
        assert 1.0 <= vals["diag/ess"] <= 6.0
        assert 0.0 <= vals["diag/active_units"] <= sum(CFG.n_latent_enc)
        assert vals["diag/active_frac"] == pytest.approx(
            vals["diag/active_units"] / sum(CFG.n_latent_enc))
        assert all(np.isfinite(v) for v in vals.values())


class TestEpochDiagnostics:
    def _setup(self):
        from iwae_replication_project_tpu.training import create_train_state
        spec = ObjectiveSpec("IWAE", k=4)
        state = create_train_state(jax.random.PRNGKey(0), CFG)
        x = jnp.asarray((np.random.RandomState(0).rand(64, 784) > 0.5)
                        .astype(np.float32))
        return spec, state, x

    def test_on_off_trainstate_bit_identical(self):
        """Diagnostics observe; they must not perturb. Same key, same data:
        params, opt state and losses agree bitwise between modes."""
        from iwae_replication_project_tpu.training.epoch import make_epoch_fn
        spec, state, x = self._setup()
        off = make_epoch_fn(spec, CFG, 64, 16, donate=False)
        on = make_epoch_fn(spec, CFG, 64, 16, donate=False,
                           diagnostics=DiagnosticsConfig(snr_window=2))
        s_off, losses_off = off(state, x)
        s_on, (losses_on, diag) = on(state, x)
        np.testing.assert_array_equal(np.asarray(losses_off),
                                      np.asarray(losses_on))
        for a, b in zip(jax.tree.leaves(s_off.params),
                        jax.tree.leaves(s_on.params)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        for k in ("diag/grad_snr", "diag/grad_snr_enc", "diag/grad_snr_dec"):
            v = float(diag[k])
            assert np.isfinite(v) and v > 0, (k, v)

    def test_disabled_config_equals_none(self):
        """DiagnosticsConfig(enabled=False) must take the byte-identical
        no-diagnostics path: plain (state, losses) return shape."""
        from iwae_replication_project_tpu.training.epoch import make_epoch_fn
        spec, state, x = self._setup()
        fn = make_epoch_fn(spec, CFG, 64, 16, donate=False,
                           diagnostics=DiagnosticsConfig(enabled=False))
        s, losses = fn(state, x)
        assert losses.shape == (4,)

    def test_block_mode_reports_last_epoch(self):
        from iwae_replication_project_tpu.training.epoch import make_epoch_fn
        spec, state, x = self._setup()
        single = make_epoch_fn(spec, CFG, 64, 16, donate=False,
                               diagnostics=DiagnosticsConfig(snr_window=2))
        block = make_epoch_fn(spec, CFG, 64, 16, donate=False,
                              diagnostics=DiagnosticsConfig(snr_window=2),
                              epochs_per_call=3)
        s1, (l1, d1) = single(state, x)
        s2, (l2, d2) = single(s1, x)
        s3, (l3, d3) = single(s2, x)
        sb, (lb, db) = block(state, x)
        np.testing.assert_array_equal(
            np.asarray(lb),
            np.concatenate([np.asarray(l) for l in (l1, l2, l3)]))
        for k in d3:
            assert float(db[k]) == pytest.approx(float(d3[k]), rel=1e-5), k

    def test_parallel_epoch_diagnostics_replicated(self, devices):
        from iwae_replication_project_tpu.parallel import make_mesh
        from iwae_replication_project_tpu.parallel.dp import (
            make_parallel_epoch_fn, replicate)
        from iwae_replication_project_tpu.training import create_train_state
        spec = ObjectiveSpec("IWAE", k=4)
        mesh = make_mesh(dp=4, sp=2)
        state = create_train_state(jax.random.PRNGKey(0), CFG)
        x = jnp.asarray((np.random.RandomState(0).rand(64, 784) > 0.5)
                        .astype(np.float32))
        fn = make_parallel_epoch_fn(
            spec, CFG, mesh, 64, 16, donate=False,
            diagnostics=DiagnosticsConfig(snr_window=2))
        state_r, (losses, diag) = fn(replicate(mesh, state),
                                     replicate(mesh, x))
        assert losses.shape == (4,)
        for k, v in diag.items():
            assert np.isfinite(float(v)) and float(v) > 0, k


# ---------------------------------------------------------------------------
# driver integration: the digits smoke of the acceptance criteria
# ---------------------------------------------------------------------------

class TestDriverIntegration:
    DIAG_KEYS = ("diag/ess", "diag/ess_frac", "diag/log_weight_var",
                 "diag/kl_q_p", "diag/active_units", "diag/grad_snr",
                 "diag/grad_snr_enc", "diag/grad_snr_dec")

    def _cfg(self, tmp_path, **over):
        from iwae_replication_project_tpu.utils.config import ExperimentConfig
        d = dict(dataset="digits", data_dir=str(tmp_path / "data"),
                 n_hidden_encoder=(16,), n_hidden_decoder=(16,),
                 n_latent_encoder=(4,), n_latent_decoder=(784,),
                 loss_function="IWAE", k=4, batch_size=32, n_stages=2,
                 eval_k=4, nll_k=8, nll_chunk=4, eval_batch_size=16,
                 activity_samples=8, save_figures=False,
                 log_dir=str(tmp_path / "runs"),
                 checkpoint_dir=str(tmp_path / "ckpt"))
        d.update(over)
        return ExperimentConfig(**d)

    def test_digits_smoke_emits_diagnostics_per_eval(self, tmp_path):
        """Acceptance: a digits smoke run emits ESS, log-weight variance and
        gradient SNR per eval into metrics.jsonl (and TensorBoard), with the
        span/registry telemetry in its own runs/<run>/telemetry stream."""
        from iwae_replication_project_tpu.experiment import run_experiment
        from tests.test_logging import decode_tfevents

        cfg = self._cfg(tmp_path)
        _, history = run_experiment(cfg, max_batches_per_pass=2,
                                    eval_subset=32)
        run_dir = os.path.join(cfg.log_dir, cfg.run_name())
        rows = [json.loads(ln) for ln in open(
            os.path.join(run_dir, "metrics.jsonl"))]
        assert [r["stage"] for r in rows] == [1, 2]  # one row per eval, only
        for row in rows:
            for key in self.DIAG_KEYS:
                assert key in row and np.isfinite(row[key]), key
            assert 1.0 <= row["diag/ess"] <= cfg.eval_k
        # the same tags reached TensorBoard
        (events_file,) = [f for f in os.listdir(run_dir)
                          if f.startswith("events.out.tfevents.")]
        tags = {v["tag"] for ev in decode_tfevents(
            os.path.join(run_dir, events_file))[1:] for v in ev["values"]}
        assert set(self.DIAG_KEYS) <= tags
        # span telemetry landed in the side stream, not metrics.jsonl
        trows = [json.loads(ln) for ln in open(
            os.path.join(run_dir, "telemetry", "metrics.jsonl"))]
        assert len(trows) == 2
        assert any(k.startswith("span/train/stage") for k in trows[-1])
        assert any(k.startswith("span/eval/") for k in trows[-1])
        # ... and the history the caller gets carries the same scalars
        assert all(k in history[-1][0] for k in self.DIAG_KEYS)

    def test_no_diagnostics_restores_pre_telemetry_stream(self, tmp_path):
        from iwae_replication_project_tpu.experiment import run_experiment

        cfg = self._cfg(tmp_path, diagnostics=False, n_stages=1)
        _, history = run_experiment(cfg, max_batches_per_pass=2,
                                    eval_subset=32)
        run_dir = os.path.join(cfg.log_dir, cfg.run_name())
        row = json.loads(open(os.path.join(
            run_dir, "metrics.jsonl")).read().strip().splitlines()[-1])
        assert not any(k.startswith("diag/") for k in row)
        assert not os.path.exists(os.path.join(run_dir, "telemetry"))

    def test_cli_flags(self):
        from iwae_replication_project_tpu.utils.config import config_from_args
        assert config_from_args([]).diagnostics is True
        assert config_from_args(["--no-diagnostics"]).diagnostics is False
        assert config_from_args(["--snr-window", "7"]).snr_window == 7


# ---------------------------------------------------------------------------
# continuous profiling plane: exposition escaping, HTTP endpoints, the
# per-dispatch profiler, and the iwae-prof statistical regression gate
# ---------------------------------------------------------------------------

from iwae_replication_project_tpu.analysis import regress  # noqa: E402
from iwae_replication_project_tpu.telemetry.exporters import (  # noqa: E402
    _escape_help,
    _escape_label,
)
from iwae_replication_project_tpu.telemetry.profiling import (  # noqa: E402
    DispatchProfiler,
    ProfilingConfig,
)

#: a value exercising every character the exposition format escapes
_TORTURE = 'back\\slash "quote"\nnewline'


def _prom_unescape(text):
    """Reference decoder for the Prometheus exposition escapes: ``\\\\``,
    ``\\n`` and (label values only) ``\\"`` — hand-rolled here so the
    round-trip test does not share code with the encoder under test."""
    out, i = [], 0
    while i < len(text):
        if text[i] == "\\" and i + 1 < len(text):
            nxt = text[i + 1]
            if nxt in ("\\", '"', "n"):
                out.append({"\\": "\\", '"': '"', "n": "\n"}[nxt])
                i += 2
                continue
        out.append(text[i])
        i += 1
    return "".join(out)


class TestPrometheusEscaping:
    def test_help_escape_round_trips(self):
        esc = _escape_help(_TORTURE)
        assert "\n" not in esc                 # a raw newline would split
        assert _prom_unescape(esc) == _TORTURE  # the comment line in two

    def test_label_escape_round_trips(self):
        esc = _escape_label(_TORTURE)
        assert "\n" not in esc
        # every double-quote survives only in escaped form
        assert all(esc[i - 1] == "\\" for i, c in enumerate(esc) if c == '"')
        assert _prom_unescape(esc) == _TORTURE

    def test_page_help_survives_hostile_metric_name(self):
        """A metric name carrying a backslash reaches the # HELP fallback
        text (``iwae counter {name!r}``); the page form must unescape back
        to exactly that text — pinned by parsing the page."""
        reg = MetricRegistry()
        name = "weird\\path/metric"
        reg.counter(name).inc()
        page = prometheus_text(reg).splitlines()
        (help_ln,) = [ln for ln in page if ln.startswith("# HELP ")
                      and "weird" in ln]
        text = help_ln.split(" ", 3)[3]
        assert _prom_unescape(text) == f"iwae counter {name!r}"
        # the sample line itself uses the sanitized name, no backslash
        assert any(ln.startswith("iwae_weird_path_metric_total ")
                   for ln in page)

    def test_quantile_labels_parse_back(self):
        reg = MetricRegistry()
        reg.histogram("h").record(0.01)
        page = prometheus_text(reg)
        import re as _re
        labels = _re.findall(r'iwae_h\{quantile="((?:[^"\\]|\\.)*)"\}', page)
        assert sorted(_prom_unescape(v) for v in labels) == \
            ["0.5", "0.95", "0.99"]


class TestMetricsEndpoints:
    """Content types, /healthz liveness, and /prof (satellites)."""

    def _get(self, port, path):
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10)

    def test_content_types_pinned(self):
        reg = MetricRegistry()
        reg.counter("hits").inc()
        srv = start_metrics_server(reg, port=0,
                                   recorder=FlightRecorder(sample_every=1))
        try:
            port = srv.server_address[1]
            resp = self._get(port, "/metrics")
            assert resp.headers["Content-Type"] == \
                "text/plain; version=0.0.4; charset=utf-8"
            resp = self._get(port, "/traces")
            assert resp.headers["Content-Type"] == \
                "application/json; charset=utf-8"
            assert "traceEvents" in json.loads(resp.read())
        finally:
            srv.shutdown()

    def test_healthz_default_is_bare_liveness(self):
        srv = start_metrics_server(MetricRegistry(), port=0)
        try:
            resp = self._get(srv.server_address[1], "/healthz")
            assert resp.status == 200
            assert resp.headers["Content-Type"] == \
                "application/json; charset=utf-8"
            assert json.loads(resp.read()) == {"ok": True}
        finally:
            srv.shutdown()

    def test_healthz_reports_provider_document(self):
        cell = [lambda: {"ok": True, "replicas": 2, "healthy": 2}]
        srv = start_metrics_server(MetricRegistry(), port=0,
                                   health=lambda: cell[0]())
        try:
            port = srv.server_address[1]
            doc = json.loads(self._get(port, "/healthz").read())
            assert doc == {"ok": True, "replicas": 2, "healthy": 2}
            # unhealthy -> 503 with the document intact
            cell[0] = lambda: {"ok": False, "replicas": 2, "healthy": 0}
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/healthz")
            assert ei.value.code == 503
            assert json.loads(ei.value.read())["healthy"] == 0
            # a RAISING provider reads as down, not as a scrape error
            def boom():
                raise RuntimeError("tier is dying")
            cell[0] = boom
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(port, "/healthz")
            assert ei.value.code == 503
            doc = json.loads(ei.value.read())
            assert doc["ok"] is False and "tier is dying" in doc["error"]
        finally:
            srv.shutdown()

    def test_prof_endpoint_serves_snapshots(self):
        reg = MetricRegistry()
        p = DispatchProfiler(reg, ProfilingConfig(peak_flops=1e12,
                                                  warmup_samples=2),
                             label="m")
        p.observe(program="serve_score", bucket=4, k_class="8", rows=4,
                  device_s=0.004, flops=2e9)
        srv = start_metrics_server(reg, port=0, profilers=(p,))
        try:
            port = srv.server_address[1]
            resp = self._get(port, "/prof")
            assert resp.headers["Content-Type"] == \
                "application/json; charset=utf-8"
            (doc,) = json.loads(resp.read())["profilers"]
            assert "m/serve_score/b4/k8" in doc["keys"]
        finally:
            srv.shutdown()

    def test_prof_endpoint_404_without_profilers(self):
        srv = start_metrics_server(MetricRegistry(), port=0)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(srv.server_address[1], "/prof")
            assert ei.value.code == 404
        finally:
            srv.shutdown()


class TestSLOClock:
    """Burn-rate windows under a non-monotonic injected clock (satellite):
    clamp to the high-water mark and count — never crash, never rewind a
    window, never mint a negative burn."""

    def test_backwards_clock_clamps_and_counts(self):
        clock = [100.0]
        reg = MetricRegistry()
        mon = SLOMonitor(registry=reg, clock=lambda: clock[0])
        mon.observe("score", 0.01)
        clock[0] = 90.0                         # clock steps BACKWARDS
        mon.observe("score", 0.01)
        w = mon.snapshot()["score"]["windows"]["5m"]
        assert w["requests"] == 2               # both observations counted
        assert w["latency_burn"] >= 0.0
        assert w["availability_burn"] >= 0.0
        assert reg.counter("slo/clock_regressions").value >= 1
        c_after = reg.counter("slo/clock_regressions").value
        clock[0] = 103.0                        # forward progress resumes
        mon.observe("score", 0.01)
        assert reg.counter("slo/clock_regressions").value == c_after
        assert mon.snapshot()["score"]["windows"]["5m"]["requests"] == 3

    def test_snapshot_under_rewound_clock_never_negative(self):
        clock = [1000.0]
        mon = SLOMonitor(registry=MetricRegistry(), clock=lambda: clock[0])
        mon.observe("score", 9.0)               # a latency violation
        clock[0] = 0.0                          # massive rewind
        snap = mon.snapshot()["score"]["windows"]
        for w in snap.values():
            assert w["requests"] == 1
            assert w["latency_burn"] >= 0.0
            assert w["availability_burn"] >= 0.0

    def test_ring_advance_never_rewinds(self):
        from iwae_replication_project_tpu.telemetry.slo import _Ring
        r = _Ring(30.0, 3)
        r.observe(100.0, True, False)
        epoch = r.epoch
        r._advance(0.0)                         # standalone safety clamp
        assert r.epoch == epoch
        assert sum(r.total) == 1 and sum(r.bad_lat) == 1


class TestProfiler:
    """DispatchProfiler: attribution keys, measured-vs-static gauges, EWMA
    drift detection, clamped intervals (schema pins for /prof)."""

    CFG = ProfilingConfig(peak_flops=1e12, peak_hbm_bytes=1e11,
                          warmup_samples=4, min_sigma_frac=0.05)
    COST = {"flops": 2e9, "bytes_accessed_fused": 1e8}

    def test_mfu_bandwidth_and_ceiling_math(self):
        reg = MetricRegistry()
        p = DispatchProfiler(reg, self.CFG, label="mnist@bf16")
        p.observe(program="serve_score", bucket=4, k_class="8", rows=4,
                  device_s=0.004, flops=2e9, cost=self.COST)
        key = "mnist@bf16/serve_score/b4/k8"
        st = p.snapshot()["keys"][key]
        # 2e9 FLOPs in 4ms = 5e11 FLOP/s over a 1e12 peak -> MFU 0.5
        assert st["last_mfu"] == pytest.approx(0.5)
        # 1e8 bytes in 4ms = 2.5e10 B/s over a 1e11 peak -> 0.25
        assert st["last_hbm_frac"] == pytest.approx(0.25)
        # roofline floor = max(2e9/1e12, 1e8/1e11) = 2ms; measured 4ms
        assert st["last_ceiling_ratio"] == pytest.approx(2.0)
        assert st["count"] == 1
        # the same numbers ride the registry (the Prometheus surface)
        assert reg.gauge(f"prof/mfu/{key}").value == pytest.approx(0.5)
        assert reg.counter("prof/dispatches").value == 1
        assert reg.counter("prof/rows").value == 4
        page = prometheus_text(reg)
        assert "iwae_prof_mfu_mnist_bf16_serve_score_b4_k8" in page
        assert "iwae_prof_device_s_mnist_bf16_serve_score_b4_k8_count 1" \
            in page

    def test_drift_trips_once_then_converges(self):
        reg = MetricRegistry()
        p = DispatchProfiler(reg, self.CFG)
        for _ in range(10):
            assert p.observe(program="serve_score", bucket=4, k_class="8",
                             rows=1, device_s=0.010) is None
        assert p.findings() == []               # a steady stream is clean
        f = p.observe(program="serve_score", bucket=4, k_class="8",
                      rows=1, device_s=0.020)
        assert f is not None
        (doc,) = p.findings()
        assert doc["kind"] == "prof/drift"
        assert doc["program"] == "serve_score"
        assert doc["bucket"] == 4 and doc["k_class"] == "8"
        assert doc["ratio"] == pytest.approx(2.0, rel=1e-6)
        assert doc["z"] > self.CFG.z_threshold
        assert reg.counter("prof/drift").value == 1
        # a PERSISTENT slowdown feeds the EWMA: the second slow sample is
        # already within the adapting baseline, no alarm storm
        p.observe(program="serve_score", bucket=4, k_class="8",
                  rows=1, device_s=0.020)
        assert len(p.findings()) == 1

    def test_warmup_arms_detector(self):
        p = DispatchProfiler(MetricRegistry(), self.CFG)
        for _ in range(3):                      # below warmup_samples=4
            p.observe(program="x", bucket=1, k_class="1", rows=1,
                      device_s=0.001)
        p.observe(program="x", bucket=1, k_class="1", rows=1,
                  device_s=0.050)               # 50x, but still cold
        assert p.findings() == []

    def test_nonpositive_intervals_clamped_and_counted(self):
        reg = MetricRegistry()
        p = DispatchProfiler(reg, self.CFG)
        assert p.observe(program="x", bucket=1, k_class="1", rows=1,
                         device_s=0.0) is None
        assert p.observe(program="x", bucket=1, k_class="1", rows=1,
                         device_s=-1.0) is None
        assert reg.counter("prof/clamped_intervals").value == 2
        assert p.snapshot()["keys"] == {}       # never fed the baseline

    def test_no_peaks_no_fabricated_gauges(self):
        reg = MetricRegistry()
        p = DispatchProfiler(reg, ProfilingConfig(warmup_samples=2),
                             peaks={"peak_flops": None,
                                    "peak_hbm_bytes": None, "source": "t"})
        p.observe(program="x", bucket=1, k_class="1", rows=1,
                  device_s=0.001, flops=1e9, cost=self.COST)
        st = p.snapshot()["keys"]["x/b1/k1"]
        assert st["last_mfu"] is None
        assert st["last_hbm_frac"] is None
        assert st["last_ceiling_ratio"] is None
        page = prometheus_text(reg)
        assert "iwae_prof_mfu_" not in page     # never a guessed peak
        assert "iwae_prof_dispatches_total 1" in page

    def test_snapshot_schema_pin(self):
        p = DispatchProfiler(MetricRegistry(), self.CFG, label="m")
        for d in (0.01, 0.01, 0.01, 0.01, 0.01, 0.1):
            p.observe(program="x", bucket=1, k_class="1", rows=1,
                      device_s=d)
        snap = p.snapshot()
        assert set(snap) == {"label", "peaks", "config", "keys",
                             "findings", "dropped_findings"}
        assert snap["label"] == "m"
        assert set(snap["config"]) == {"ewma_alpha", "z_threshold",
                                       "warmup_samples"}
        (st,) = snap["keys"].values()
        assert set(st) == {"count", "ewma_s", "sigma_s", "last_s",
                           "last_mfu", "last_hbm_frac",
                           "last_ceiling_ratio", "last_z"}
        (finding,) = snap["findings"]
        assert set(finding) == {"kind", "key", "program", "model", "bucket",
                                "k_class", "measured_s", "baseline_s",
                                "sigma_s", "z", "ratio", "seq"}
        json.dumps(snap)                        # wire-safe by construction


class TestRegress:
    """iwae-prof: direction heuristic, rank test, and the end-to-end gate
    (exit codes + the shared --json envelope, schema pinned here)."""

    OLD = {"wall_s": 1.0, "rows_per_sec": 1000.0,
           "pairs": {"pairs_s": [0.100, 0.101, 0.099, 0.102, 0.098]}}

    def test_direction_heuristic(self):
        assert regress.direction_for("a/rows_per_sec") == 1
        assert regress.direction_for("x/speedup") == 1
        assert regress.direction_for("wall_s") == -1
        assert regress.direction_for("overhead_pct_best") == -1
        assert regress.direction_for("score/latency_p99_s") == -1
        # polarity lives in the LEAF name only: a directional parent does
        # not rescue an opaque leaf
        assert regress.direction_for("latency/p99") == 0
        assert regress.direction_for("off_over_on_pairs") == 0
        assert regress.direction_for("n_devices") == 0

    def test_rank_sum_p(self):
        same = [1.0, 2.0, 3.0, 4.0, 5.0]
        assert regress.rank_sum_p(same, list(same)) == pytest.approx(1.0)
        assert regress.rank_sum_p([], [1.0]) == 1.0
        a = [1.00, 1.01, 0.99, 1.02, 0.98]
        b = [2.00, 2.01, 1.99, 2.02, 1.98]
        assert regress.rank_sum_p(a, b) < 0.05

    def test_extract_metrics_paths(self):
        m = regress.extract_metrics(
            {"wall_s": 1.5, "flag": True, "pairs_s": [0.1, 0.2],
             "nested": {"x": 2}, "rows": [{"y": 3}, {"y": 4}]})
        assert m == {"wall_s": [1.5], "pairs_s": [0.1, 0.2],
                     "nested/x": [2.0], "rows[0]/y": [3.0],
                     "rows[1]/y": [4.0]}      # bools are config, skipped

    def _write(self, tmp_path, name, doc):
        p = tmp_path / name
        p.write_text(json.dumps(doc))
        return str(p)

    def test_diff_flags_2x_slowdown_and_names_the_metric(self, tmp_path,
                                                         capsys):
        new = {"wall_s": 2.0, "rows_per_sec": 400.0,
               "pairs": {"pairs_s": [v * 2 for v in
                                     self.OLD["pairs"]["pairs_s"]]}}
        old_p = self._write(tmp_path, "old.json", self.OLD)
        new_p = self._write(tmp_path, "bench.json", new)
        assert regress.main(["--diff", old_p, new_p]) == 1
        out = capsys.readouterr().out
        assert "REGRESSION bench:pairs/pairs_s" in out
        assert "REGRESSION bench:wall_s" in out
        # the --json form carries the same findings in the envelope
        assert regress.main(["--diff", old_p, new_p, "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert set(doc) == {"tool", "schema", "mode", "ok", "findings",
                            "data"}
        assert doc["tool"] == "iwae-prof"
        assert doc["schema"] == regress.ENVELOPE_SCHEMA
        assert doc["mode"] == "diff" and doc["ok"] is False
        keys = {(f["artifact"], f["key"]) for f in doc["findings"]}
        assert ("bench", "pairs/pairs_s") in keys
        for f in doc["findings"]:
            assert f["kind"] == "perf/regression"
            assert f["rel_change"] > 0 or f["key"] == "rows_per_sec"

    def test_self_diff_and_collected_baseline_pass(self, tmp_path, capsys):
        a = self._write(tmp_path, "a_bench.json", self.OLD)
        baseline = str(tmp_path / "baseline.json")
        assert regress.main(["--collect", a, "--out", baseline]) == 0
        doc = json.loads(open(baseline).read())
        assert doc["kind"] == regress.BASELINE_KIND
        assert set(doc["artifacts"]) == {"a_bench"}
        assert regress.main(["--diff", baseline, a]) == 0
        assert "0 regression(s)" in capsys.readouterr().out

    def test_within_noise_shift_not_flagged(self, tmp_path):
        # the recorded spread's rel-IQR is ~20%: a 5% median shift in the
        # bad direction must NOT gate
        new = {"wall_s": 1.04,       # scalar: under the 10% scalar floor
               "rows_per_sec": 980.0,
               "pairs": {"pairs_s": [v * 1.05 for v in
                                     [0.10, 0.11, 0.09, 0.12, 0.08]]}}
        old = {"wall_s": 1.0, "rows_per_sec": 1000.0,
               "pairs": {"pairs_s": [0.10, 0.11, 0.09, 0.12, 0.08]}}
        old_p = self._write(tmp_path, "old.json", old)
        new_p = self._write(tmp_path, "bench.json", new)
        assert regress.main(["--diff", old_p, new_p]) == 0

    def test_missing_file_is_usage_error(self, tmp_path):
        assert regress.main(
            ["--diff", str(tmp_path / "nope.json"),
             str(tmp_path / "also_nope.json")]) == 2

    def test_trace_cli_shares_the_envelope(self, capsys):
        """iwae-trace --json and iwae-prof --json emit ONE convention
        (satellite): same keys, same schema version."""
        from iwae_replication_project_tpu.serving.frontend import ServingTier
        from iwae_replication_project_tpu.telemetry import trace_cli
        t = ServingTier([_TraceFakeEngine()], port=0,
                        recorder=FlightRecorder(sample_every=1))
        t.start()
        try:
            rc = trace_cli.main([f"127.0.0.1:{t.port}", "--stats", "--json"])
        finally:
            t.stop(timeout_s=10)
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert set(doc) == {"tool", "schema", "mode", "ok", "findings",
                            "data"}
        assert doc["tool"] == "iwae-trace"
        assert doc["schema"] == regress.ENVELOPE_SCHEMA
        assert doc["mode"] == "stats" and doc["ok"] is True
        assert doc["findings"] == []
        assert "retained" in doc["data"]
