"""TF2 backend tests — the reference's own eager execution style restored
behind the facade (backends/tf2_ref.py). Skipped wholesale when TensorFlow is
not importable, keeping the backend="tf2" gate honest either way."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from iwae_replication_project_tpu.api import FlexibleModel  # noqa: E402

ARCH = dict(n_hidden_encoder=[12], n_hidden_decoder=[12],
            n_latent_encoder=[4], n_latent_decoder=[12])
ARCH2L = dict(n_hidden_encoder=[10, 8], n_hidden_decoder=[8, 10],
              n_latent_encoder=[5, 3], n_latent_decoder=[5, 12])


def make_x(n=8, d=12, seed=1):
    return (np.random.RandomState(seed).rand(n, d) > 0.5).astype(np.float32)


def build(**kw):
    args = dict(ARCH)
    args.update(kw)
    bias = args.pop("dataset_bias", None)
    return FlexibleModel(args.pop("n_hidden_encoder"),
                         args.pop("n_hidden_decoder"),
                         args.pop("n_latent_encoder"),
                         args.pop("n_latent_decoder"),
                         dataset_bias=bias, backend="tf2", **args)


class TestDispatchAndSurface:
    def test_facade_dispatches_to_tf2_class(self):
        from iwae_replication_project_tpu.backends.tf2_ref import (
            TF2FlexibleModel)
        assert isinstance(build(), TF2FlexibleModel)

    def test_reference_method_surface_smoke(self):
        """Every reference method exists and returns finite values — the
        north-star 'alongside the existing TF2 path' sentence, executed."""
        m = build(loss_function="IWAE", k=4, seed=0).compile()
        x = make_x()
        assert m.get_log_weights(x, 3).shape == (3, 8)
        for val in (m.get_L(x, 6), m.get_L_k(x, 4), m.get_L_V1(x, 4),
                    m.get_L_alpha(x, 4, 0.5), m.get_L_power_p(x, 4, 2.0),
                    m.get_L_median(x, 4), m.get_L_CIWAE(x, 4, 0.3),
                    m.get_L_MIWAE(x, 2, 2), m.get_NLL(x, k=8, chunk=4),
                    m.get_E_qhIx_log_pxIh(x, 4), m.get_Dkl_qhIx_ph(x, 4),
                    m.get_reconstruction_loss(x)):
            assert np.isfinite(float(val))
        r = m.train_step(x)
        assert np.isfinite(r["IWAE"])
        assert m.generate(3).shape == (3, 12)


@pytest.mark.slow
class TestTF2Semantics:
    def test_estimator_parity_on_shared_log_weights(self):
        """The tf2 bound reducers agree with the JAX reducers on identical
        log-weight tensors (estimator-level parity, no sampling noise)."""
        import jax
        from iwae_replication_project_tpu.backends.tf2_ref import (
            TF2FlexibleModel)
        from iwae_replication_project_tpu.objectives import (
            ObjectiveSpec, bound_from_log_weights)
        lw_np = (np.random.RandomState(0).randn(12, 5) * 3).astype(np.float32)
        jlw = jax.numpy.asarray(lw_np)
        tlw = tf.convert_to_tensor(lw_np)
        pairs = [
            (bound_from_log_weights(ObjectiveSpec("IWAE", k=12), jlw),
             TF2FlexibleModel._iwae(tlw)),
            (bound_from_log_weights(ObjectiveSpec("VAE", k=12), jlw),
             tf.reduce_mean(tlw)),
            (bound_from_log_weights(ObjectiveSpec("MIWAE", k=12, k2=3), jlw),
             TF2FlexibleModel._miwae(tlw, 3)),
        ]
        for jval, tval in pairs:
            np.testing.assert_allclose(float(jval), float(tval), rtol=1e-5)

    def test_weight_tied_statistical_parity_vs_jax(self):
        """Tied weights -> the tf2 and JAX bounds are MC estimates of the SAME
        quantity; agree within a few standard errors (the same corridor the
        torch oracle is held to)."""
        x = make_x(32, seed=3)
        bias = np.clip(x.mean(0), 0.05, 0.95)
        jm = FlexibleModel(**{k: list(v) for k, v in ARCH.items()},
                           pixel_means=bias, loss_function="VAE", k=8,
                           backend="jax", seed=0).compile()
        jm.fit(x, epochs=5, batch_size=16)
        tm = build(pixel_means=bias, loss_function="VAE", k=8, seed=0).compile()
        tm.load_jax_params(jm.params)

        jv = np.array([float(jm.get_L(x, 64)) for _ in range(6)])
        tv = np.array([float(tm.get_L(x, 64)) for _ in range(6)])
        se = np.sqrt(jv.var(ddof=1) / len(jv) + tv.var(ddof=1) / len(tv))
        assert abs(jv.mean() - tv.mean()) < max(4 * se, 0.02), (
            jv.mean(), tv.mean(), se)

        jn = np.array([float(jm.get_NLL(x, k=200, chunk=50)) for _ in range(4)])
        tn = np.array([float(tm.get_NLL(x, k=200, chunk=50)) for _ in range(4)])
        se = np.sqrt(jn.var(ddof=1) / len(jn) + tn.var(ddof=1) / len(tn))
        assert abs(jn.mean() - tn.mean()) < max(4 * se, 0.02), (
            jn.mean(), tn.mean(), se)

    def test_same_seed_reproducible(self):
        """seed= must make tf2 runs re-derivable (sampling AND init)."""
        losses = []
        for _ in range(2):
            m = build(loss_function="IWAE", k=4, seed=3).compile()
            losses.append(m.fit(make_x(16, seed=9), epochs=2,
                                batch_size=8)["loss"])
        np.testing.assert_allclose(losses[0], losses[1], rtol=1e-6)

    def test_vae_v1_rejects_multilayer(self):
        """VAE_V1 is single-stochastic-layer only — refuse L>=2 like the JAX
        path instead of silently returning a wrong bound."""
        m = FlexibleModel(**{k: list(v) for k, v in ARCH2L.items()},
                          dataset_bias=None, loss_function="IWAE", k=4,
                          backend="tf2", seed=0).compile()
        with pytest.raises(ValueError, match="single-stochastic-layer"):
            m.get_L_V1(make_x(8), 4)

    def test_save_load_weights_cross_backend(self, tmp_path):
        """The tf2 backend shares the facade checkpoint format: a jax
        checkpoint loads bit-for-bit, a mismatched architecture refuses."""
        import jax
        jm = FlexibleModel(**{k: list(v) for k, v in ARCH.items()},
                           dataset_bias=None, loss_function="IWAE", k=4,
                           backend="jax", seed=0).compile()
        path = str(tmp_path / "w")
        jm.save_weights(path)
        m = build(loss_function="IWAE", k=4, seed=7).compile()
        m.load_weights(path)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                                np.asarray(b)),
                     jm.params, m._weights_pytree())
        wrong = FlexibleModel(**{k: list(v) for k, v in ARCH2L.items()},
                              dataset_bias=None, loss_function="IWAE", k=4,
                              backend="tf2", seed=0).compile()
        with pytest.raises(ValueError):
            wrong.load_weights(path)

    def test_fit_epochs_compose(self):
        """fit(epochs=2) == fit(1); fit(1): the shuffle stream is driven by a
        carried per-epoch counter, not the per-batch `epoch` counter, so a
        multi-epoch fit is re-derivable regardless of call history
        (VERDICT r3 weak #5)."""
        x = make_x(24, seed=11)
        a = build(loss_function="IWAE", k=4, seed=5).compile()
        ha = a.fit(x, epochs=2, batch_size=8)["loss"]
        b = build(loss_function="IWAE", k=4, seed=5).compile()
        hb = (b.fit(x, epochs=1, batch_size=8)["loss"]
              + b.fit(x, epochs=1, batch_size=8)["loss"])
        np.testing.assert_allclose(ha, hb, rtol=1e-6)

    def test_training_descends_2l(self):
        m = FlexibleModel(**{k: list(v) for k, v in ARCH2L.items()},
                          dataset_bias=None, loss_function="IWAE", k=4,
                          backend="tf2", seed=0).compile()
        x = make_x(48, seed=5)
        hist = m.fit(x, epochs=6, batch_size=16)
        assert hist["loss"][-1] < hist["loss"][0]

    @pytest.mark.parametrize("name", ["DReG", "STL", "PIWAE"])
    def test_modified_estimators_train(self, name):
        m = FlexibleModel(**{k: list(v) for k, v in ARCH2L.items()},
                          dataset_bias=None, loss_function=name, k=6,
                          k2=2 if name == "PIWAE" else 1,
                          backend="tf2", seed=0).compile()
        x = make_x(16, seed=6)
        hist = m.fit(x, epochs=2, batch_size=8)
        assert all(np.isfinite(v) for v in hist["loss"])

    def test_stats_driver_schema(self):
        m = build(loss_function="IWAE", k=4, seed=1).compile()
        x = make_x(16, seed=7)
        res, res2 = m.get_training_statistics(x, 4, batch_size=8, nll_k=16,
                                              nll_chunk=8, activity_samples=16)
        for key in ("VAE", "IWAE", "NLL", "reconstruction_loss", "LL_pruned",
                    "nll_chunk"):
            assert key in res and np.isfinite(res[key]), key
        assert len(res2["number_of_active_units"]) == 1

    def test_staged_experiment_runs_on_tf2_backend(self, tmp_path):
        """run_experiment(backend='tf2'): the reference's experiment flow on
        the reference's own execution style."""
        import json
        import os

        from iwae_replication_project_tpu.experiment import run_experiment
        from iwae_replication_project_tpu.utils.config import ExperimentConfig
        cfg = ExperimentConfig(
            dataset="binarized_mnist", data_dir=str(tmp_path / "data"),
            n_hidden_encoder=(12,), n_hidden_decoder=(12,),
            n_latent_encoder=(4,), n_latent_decoder=(784,),
            loss_function="IWAE", k=4, batch_size=32, n_stages=2,
            eval_k=4, nll_k=8, nll_chunk=4, eval_batch_size=16,
            activity_samples=8, backend="tf2",
            log_dir=str(tmp_path / "runs"),
            checkpoint_dir=str(tmp_path / "ckpt"))
        mdl, history = run_experiment(cfg, max_batches_per_pass=2,
                                      eval_subset=16)
        assert len(history) == 2
        assert np.isfinite(history[-1][0]["NLL"])
        path = os.path.join(cfg.log_dir, cfg.run_name() + "-tf2",
                            "metrics.jsonl")
        rec = json.loads(open(path).read().strip().splitlines()[-1])
        assert rec["stage"] == 2.0
