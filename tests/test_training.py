"""Training-layer tests: schedule values, state creation, descent, LR
injection, checkpoint round-trip."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from iwae_replication_project_tpu.models import ModelConfig
from iwae_replication_project_tpu.objectives import ObjectiveSpec
from iwae_replication_project_tpu.training import (
    burda_stage_lr,
    burda_stages,
    create_train_state,
    make_adam,
    make_train_step,
)
from iwae_replication_project_tpu.training.train_step import set_learning_rate
from iwae_replication_project_tpu.utils.checkpoint import (
    latest_step,
    restore_latest,
    save_checkpoint,
)

CFG = ModelConfig(n_hidden_enc=(16,), n_latent_enc=(4,),
                  n_hidden_dec=(16,), n_latent_dec=(12,), x_dim=12)


def make_batch(b=16, d=12):
    return (jax.random.uniform(jax.random.PRNGKey(42), (b, d)) > 0.5).astype(jnp.float32)


class TestSchedule:
    def test_burda_lr_endpoints(self):
        """Stage 1 -> 1e-3, stage 8 -> 1e-4 (experiment_example.py:76)."""
        np.testing.assert_allclose(burda_stage_lr(1), 1e-3, rtol=1e-9)
        np.testing.assert_allclose(burda_stage_lr(8), 1e-4, rtol=1e-9)

    def test_total_passes_3280(self):
        """Sum 3^(i-1), i=1..8 == 3280 (PDF §3.4)."""
        assert sum(p for _, _, p in burda_stages(8)) == 3280

    def test_monotone_decreasing(self):
        lrs = [lr for _, lr, _ in burda_stages(8)]
        assert all(a >= b for a, b in zip(lrs, lrs[1:]))


class TestTrainStep:
    def test_state_shapes_and_bias(self, rng):
        bias = np.linspace(-1, 1, 12).astype(np.float32)
        state = create_train_state(rng, CFG, output_bias=bias)
        np.testing.assert_allclose(np.asarray(state.params["out"]["out"]["b"]),
                                   bias, rtol=1e-6)

    def test_loss_decreases(self, rng):
        state = create_train_state(rng, CFG)
        step = make_train_step(ObjectiveSpec("IWAE", k=8), CFG, donate=False)
        batch = make_batch()
        losses = []
        for _ in range(30):
            state, m = step(state, batch)
            losses.append(float(m["loss"]))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])
        assert int(state.step) == 30

    def test_lr_injection_preserves_moments(self, rng):
        state = create_train_state(rng, CFG, lr=1e-3)
        step = make_train_step(ObjectiveSpec("VAE", k=4), CFG, donate=False)
        state, _ = step(state, make_batch())
        # after one step, moments are nonzero
        mu_leaves = jax.tree.leaves(state.opt_state.inner_state[0].mu)
        assert any(float(jnp.max(jnp.abs(l))) > 0 for l in mu_leaves)
        state2 = set_learning_rate(state, 5e-4)
        np.testing.assert_allclose(
            float(state2.opt_state.hyperparams["learning_rate"]), 5e-4)
        # the old state must be untouched (no aliased in-place mutation)
        np.testing.assert_allclose(
            float(state.opt_state.hyperparams["learning_rate"]), 1e-3)
        # moments unchanged
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                                np.asarray(b)),
                     state.opt_state.inner_state[0].mu,
                     state2.opt_state.inner_state[0].mu)

    def test_adam_eps_default(self):
        """Reference parity: eps=1e-4 (experiment_example.py:39)."""
        opt = make_adam()
        state = opt.init({"w": jnp.zeros(3)})
        # inject_hyperparams stores only injected hyperparams; eps is traced
        # into the update fn — verify numerically: with g=0 update must be 0,
        # with tiny g the eps dominates the denominator.
        g = {"w": jnp.full(3, 1e-8)}
        updates, _ = opt.update(g, state, {"w": jnp.zeros(3)})
        # adam first step: m_hat = g, v_hat = g^2 ; update = lr*m_hat/(sqrt(v_hat)+eps)
        expected = -1e-3 * 1e-8 / (1e-8 + 1e-4)
        np.testing.assert_allclose(np.asarray(updates["w"]),
                                   np.full(3, expected), rtol=1e-4)


class TestCheckpoint:
    def test_roundtrip(self, rng, tmp_path):
        d = os.path.join(str(tmp_path), "ckpt")
        state = create_train_state(rng, CFG)
        step = make_train_step(ObjectiveSpec("IWAE", k=4), CFG, donate=False)
        state, _ = step(state, make_batch())
        save_checkpoint(d, 1, state, stage=3, config_json='{"a": 1}')
        assert latest_step(d) == 1

        template = create_train_state(jax.random.PRNGKey(99), CFG)
        restored = restore_latest(d, template)
        assert restored is not None
        rstep, rstate, rstage, rpasses = restored
        assert rstep == 1 and rstage == 3
        assert rpasses is None  # no passes_done given -> stage complete
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                                np.asarray(b)),
                     state.params, rstate.params)
        jax.tree.map(lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                                np.asarray(b)),
                     state.opt_state.inner_state[0].mu,
                     rstate.opt_state.inner_state[0].mu)

    def test_passes_done_roundtrip(self, rng, tmp_path):
        """Mid-stage checkpoints carry (stage, passes_done); stage-boundary
        checkpoints (and every pre-r5 payload) restore passes_done=None."""
        d = os.path.join(str(tmp_path), "ckpt")
        state = create_train_state(rng, CFG)
        save_checkpoint(d, 1, state, stage=5, passes_done=81)
        template = create_train_state(jax.random.PRNGKey(99), CFG)
        _, _, rstage, rpasses = restore_latest(d, template)
        assert (rstage, rpasses) == (5, 81)

    def test_restore_missing_returns_none(self, rng, tmp_path):
        template = create_train_state(rng, CFG)
        assert restore_latest(os.path.join(str(tmp_path), "nope"), template) is None

    def test_refuses_resume_on_config_mismatch(self, rng, tmp_path):
        """A checkpoint written by one experiment config must not silently
        restore into a different one (ADVICE r1 medium)."""
        from iwae_replication_project_tpu.utils.config import ExperimentConfig
        d = os.path.join(str(tmp_path), "ckpt")
        state = create_train_state(rng, CFG)
        written = ExperimentConfig(loss_function="L_alpha", alpha=0.0)
        save_checkpoint(d, 1, state, stage=2, config_json=written.to_json())
        other = ExperimentConfig(loss_function="L_alpha", alpha=0.25)
        with pytest.raises(ValueError, match="different"):
            restore_latest(d, state, expect_config_json=other.to_json())
        # matching science fields resume fine even if output dirs moved
        moved = ExperimentConfig(loss_function="L_alpha", alpha=0.0,
                                 log_dir="elsewhere")
        assert restore_latest(d, state,
                              expect_config_json=moved.to_json()) is not None

    def test_resume_warns_on_compute_dtype_drift(self, rng, tmp_path, capsys):
        """compute_dtype is an execution knob (not a science field), so
        cross-dtype resume is legal — but it must be flagged, or a pre-r5
        f32 checkpoint silently continues as a mixed-precision trajectory
        under the r5 bfloat16 default."""
        from iwae_replication_project_tpu.utils.config import ExperimentConfig
        d = os.path.join(str(tmp_path), "ckpt")
        state = create_train_state(rng, CFG)
        f32_cfg = ExperimentConfig(compute_dtype="float32")
        save_checkpoint(d, 1, state, stage=2, config_json=f32_cfg.to_json())
        bf16_cfg = ExperimentConfig(compute_dtype="bfloat16")
        assert restore_latest(d, state,
                              expect_config_json=bf16_cfg.to_json()) is not None
        out = capsys.readouterr().out
        assert "compute_dtype" in out and "resuming under" in out
        # same dtype -> no note
        assert restore_latest(d, state,
                              expect_config_json=f32_cfg.to_json()) is not None
        assert "resuming under" not in capsys.readouterr().out

    def test_retention(self, rng, tmp_path):
        d = os.path.join(str(tmp_path), "ckpt")
        state = create_train_state(rng, CFG)
        for s in range(5):
            save_checkpoint(d, s, state, stage=s, keep=2)
        assert latest_step(d) == 4
