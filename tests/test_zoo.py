"""Zoo + objective switching + profiling utility tests."""

import numpy as np
import pytest

from iwae_replication_project_tpu import zoo
from iwae_replication_project_tpu.utils.config import ExperimentConfig


class TestZoo:
    def test_all_presets_valid(self):
        cfgs = zoo.configs()
        for name, cfg in cfgs.items():
            cfg.model_config()       # validates architecture lists
            cfg.objective_spec()     # validates objective name/hparams
            assert cfg.run_name()

    def test_expected_coverage(self):
        """Every reference table is represented (BASELINE.md Tables 1-10)."""
        names = set(zoo.configs())
        assert sum(n.startswith("table1-") for n in names) == 12
        assert sum(n.startswith("table2-") for n in names) == 12
        assert sum(n.startswith("table3-") for n in names) == 8
        assert sum(n.startswith("table4-") for n in names) == 4
        assert sum(n.startswith("table5-") for n in names) == 3
        assert sum(n.startswith("table6-") for n in names) == 1
        assert sum(n.startswith("table7-") for n in names) == 4
        assert sum(n.startswith("table8-") for n in names) == 3
        assert sum(n.startswith("table9-") for n in names) == 4
        assert sum(n.startswith("table10-") for n in names) == 4
        assert "northstar-iwae-2l-k50" in names
        assert "dreg-k50-fashion" in names and "stl-k50-fashion" in names

    def test_northstar_matches_reference_architecture(self):
        cfg = zoo.get("northstar-iwae-2l-k50")
        assert cfg.n_hidden_encoder == (200, 100)
        assert cfg.n_latent_encoder == (100, 50)
        assert cfg.n_hidden_decoder == (100, 200)
        assert cfg.n_latent_decoder == (100, 784)
        assert cfg.loss_function == "IWAE" and cfg.k == 50

    def test_miwae_table9_spec(self):
        spec = zoo.get("table9-miwae-5x10").objective_spec()
        assert spec.name == "MIWAE" and spec.k == 50 and spec.k2 == 10

    def test_unknown_preset_suggests(self):
        with pytest.raises(KeyError, match="did you mean"):
            zoo.get("table1-iwae-2l-k51")


class TestObjectiveSwitching:
    def test_switch_spec_by_stage(self):
        cfg = zoo.get("table10-iwae-to-vae-k1")
        assert cfg.objective_spec(4).name == "IWAE"
        assert cfg.objective_spec(4).k == 50
        assert cfg.objective_spec(5).name == "VAE"
        assert cfg.objective_spec(5).k == 1
        assert cfg.objective_spec().name == "IWAE"

    def test_switch_in_run_experiment(self, tmp_path):
        from iwae_replication_project_tpu.experiment import run_experiment
        cfg = ExperimentConfig(
            dataset="binarized_mnist", data_dir=str(tmp_path / "d"),
            n_hidden_encoder=(16,), n_hidden_decoder=(16,),
            n_latent_encoder=(4,), n_latent_decoder=(784,),
            loss_function="IWAE", k=4, batch_size=32, n_stages=2,
            switch_stage=2, switch_loss="VAE", switch_k=2,
            eval_k=4, nll_k=8, nll_chunk=4, eval_batch_size=16,
            activity_samples=8,
            log_dir=str(tmp_path / "runs"), checkpoint_dir=str(tmp_path / "ck"))
        _, history = run_experiment(cfg, max_batches_per_pass=2, eval_subset=32)
        assert len(history) == 2
        assert all(np.isfinite(h[0]["NLL"]) for h in history)


class TestPresetCli:
    def test_preset_flag(self):
        from iwae_replication_project_tpu.utils.config import config_from_args
        cfg = config_from_args(["--preset", "table7-power2.0", "--n-stages", "3"])
        assert cfg.loss_function == "L_power_p" and cfg.p == 2.0
        assert cfg.n_stages == 3  # CLI override on top of preset

    def test_list_presets_exits(self, capsys):
        from iwae_replication_project_tpu.utils.config import config_from_args
        with pytest.raises(SystemExit):
            config_from_args(["--list-presets"])
        assert "northstar-iwae-2l-k50" in capsys.readouterr().out


class TestProfiling:
    def test_step_timer(self):
        from iwae_replication_project_tpu.utils.profiling import StepTimer
        t = StepTimer()
        for _ in range(10):
            with t:
                pass
        s = t.summary()
        assert s["count"] == 10
        assert s["p50_s"] >= 0 and s["max_s"] >= s["p50_s"]
        t.reset()
        assert t.summary() == {"count": 0}

    def test_nan_guard_raises_on_nan(self):
        import jax
        import jax.numpy as jnp
        from iwae_replication_project_tpu.utils.profiling import nan_guard
        with nan_guard():
            with pytest.raises(FloatingPointError):
                jax.jit(lambda v: jnp.log(v))(jnp.asarray(-1.0)).block_until_ready()
        # restored afterwards
        assert not jax.config.jax_debug_nans

    def test_assert_finite_tree(self):
        import jax.numpy as jnp
        from iwae_replication_project_tpu.utils.profiling import assert_finite_tree
        assert_finite_tree({"a": jnp.ones(3)}, "params")
        with pytest.raises(AssertionError, match="grads"):
            assert_finite_tree({"a": jnp.asarray(float("nan"))}, "grads")

    def test_trace_writes_profile(self, tmp_path):
        import jax
        import jax.numpy as jnp
        import os
        from iwae_replication_project_tpu.utils.profiling import trace
        with trace(str(tmp_path)):
            jnp.sum(jnp.ones(16)).block_until_ready()
        found = []
        for root, _, files in os.walk(tmp_path):
            found.extend(files)
        assert found, "no profile artifacts written"
